"""Chaos suite: full cross-silo deployments under seeded fault plans.

Every test here is deterministic (hash-seeded fault draws, no wall-clock
randomness) and bounded (short round/handshake deadlines, thread joins with
timeouts) — a hang is a failure, never a stall of the suite.
"""

import collections
import queue
import threading
import time

import numpy as np
import pytest

import fedml_tpu
from fedml_tpu.comm import LoopbackHub, Message
from fedml_tpu.comm.resilience import FaultPlan
from fedml_tpu.core import telemetry
from fedml_tpu.cross_silo import FedML_Horizontal, MyMessage
from fedml_tpu.cross_silo.chaos import run_chaos_drill

pytestmark = pytest.mark.chaos


@pytest.fixture(autouse=True)
def _fresh_telemetry():
    telemetry.configure(enabled=True, reset=True)
    yield
    telemetry.configure(enabled=True, reset=True)


def _args(**kw):
    base = dict(
        dataset="mnist", model="lr", debug_small_data=True,
        client_num_in_total=2, client_num_per_round=2, comm_round=1,
        learning_rate=0.1, epochs=1, batch_size=8, frequency_of_the_test=1,
        random_seed=0,
    )
    base.update(kw)
    return fedml_tpu.init(config=base)


def _drain(q):
    out = []
    while True:
        try:
            data = q.get_nowait()
        except queue.Empty:
            return out
        if data is not None:
            out.append(Message.from_bytes(data))


def _online(sender):
    m = Message(MyMessage.MSG_TYPE_C2S_CLIENT_STATUS, sender, 0)
    m.add_params(MyMessage.MSG_ARG_KEY_CLIENT_STATUS,
                 MyMessage.MSG_CLIENT_STATUS_IDLE)
    return m


def _upload(server, sender, round_idx=0):
    import jax

    delta = jax.tree_util.tree_map(
        lambda x: np.zeros_like(np.asarray(x)),
        server.aggregator.get_global_model_params())
    m = Message(MyMessage.MSG_TYPE_C2S_SEND_MODEL_TO_SERVER, sender, 0)
    m.add_params(MyMessage.MSG_ARG_KEY_MODEL_PARAMS, delta)
    m.add_params(MyMessage.MSG_ARG_KEY_NUM_SAMPLES, 8)
    m.add_params(MyMessage.MSG_ARG_KEY_ROUND_INDEX, round_idx)
    return m


def _wait_for(predicate, timeout=10.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return predicate()


# --- seeded drills (drop / crash / transient send failures) ------------------


def test_chaos_drill_packet_loss_completes_all_rounds():
    """20% of every message type dropped, every round — straggler timeouts
    and resends must still walk the run to completion."""
    result = run_chaos_drill(join_timeout_s=90.0)  # seeded drop-20% defaults
    assert result.ok, result.summary()
    assert result.rounds_completed == 3
    assert result.faults_injected.get("drop", 0) >= 1, result.summary()
    # the run didn't just terminate — it still trained something sane
    final = result.history[-1]
    assert np.isfinite(final.get("test_acc", np.nan)), final
    assert final["test_acc"] > 0.2, final


def test_chaos_drill_client_crash_completes_all_rounds():
    """One client dies at round 1 and stays dead — the round closes on the
    straggler timeout with the survivors and the run still finishes."""
    result = run_chaos_drill(join_timeout_s=90.0, fault_drop_rate=0.0,
                             fault_crash_rank=3, fault_crash_at_round=1)
    assert result.ok, result.summary()
    assert result.faults_injected.get("crash", 0) == 1, result.summary()


def test_chaos_drill_transient_send_failures_are_retried():
    result = run_chaos_drill(join_timeout_s=90.0, fault_drop_rate=0.0,
                             fault_fail_send_rate=0.3)
    assert result.ok, result.summary()
    assert result.send_retries >= 1, result.summary()
    assert result.faults_injected.get("fail_send", 0) >= 1, result.summary()


# --- server restart from the round-state checkpoint --------------------------


def test_chaos_server_restart_resumes_from_checkpoint(tmp_path):
    """Kill the server after round 0 (seeded crash plan), then boot a fresh
    server process on the same transport with the same checkpoint path: it
    must resume at round 1 — not round 0 — and finish the remaining rounds
    with the clients that never went away."""
    cfg = dict(
        dataset="mnist", model="lr", debug_small_data=True,
        client_num_in_total=2, client_num_per_round=2, comm_round=3,
        learning_rate=0.1, epochs=1, batch_size=8, frequency_of_the_test=1,
        random_seed=0,
        round_ckpt_path=str(tmp_path / "round_state.msgpack"),
        ckpt_every_rounds=1,
    )
    # phase 1: the incarnation that dies. The plan crashes rank 0 at round 1,
    # i.e. right after round 0 completes (and checkpoints) but before any
    # round-1 SYNC reaches a client.
    args_a = fedml_tpu.init(config={**cfg, "fault_crash_rank": 0,
                                    "fault_crash_at_round": 1})
    hub = LoopbackHub()
    server_a = FedML_Horizontal(args_a, 0, 2, backend="LOOPBACK", hub=hub)
    clients = [FedML_Horizontal(args_a, rank, 2, backend="LOOPBACK", hub=hub)
               for rank in (1, 2)]
    client_threads = [threading.Thread(target=c.run, daemon=True)
                      for c in clients]
    for t in client_threads:
        t.start()
    server_a.start()
    thread_a = threading.Thread(target=server_a.run, daemon=True)
    thread_a.start()
    thread_a.join(timeout=60)
    assert not thread_a.is_alive(), "crashed server's loop did not exit"
    assert len(server_a.history) == 1  # died after exactly one round
    assert server_a.com_manager.crashed

    # phase 2: a fresh server process (no fault plan) on the same hub + path.
    # A real restart binds a fresh endpoint; here the hub queue is shared
    # between incarnations, so clear the dead server's leftover poison pill.
    stale = hub.register(0)
    while not stale.empty():
        stale.get_nowait()
    args_b = fedml_tpu.init(config=cfg)
    server_b = FedML_Horizontal(args_b, 0, 2, backend="LOOPBACK", hub=hub)
    assert server_b.round_idx == 1  # resumed, not restarted
    thread_b = threading.Thread(target=server_b.run, daemon=True)
    thread_b.start()
    server_b.start()  # re-probes; the still-running clients answer ONLINE
    thread_b.join(timeout=90)
    assert not thread_b.is_alive(), "resumed server did not finish"
    assert [h["round"] for h in server_b.history] == [1, 2]
    assert server_b.round_idx == 3
    for t in client_threads:
        t.join(timeout=10)
        assert not t.is_alive()
    # clients followed the resumed numbering from the round-stamped INIT
    assert all(c.round_idx == 2 for c in clients)


# --- rejoin + handshake deadline (server FSM, driven synchronously) ----------


def test_chaos_midrun_online_report_gets_current_sync():
    """A client that restarts mid-round re-announces ONLINE; the server's
    rejoin path answers with the CURRENT round's model instead of leaving it
    idle until FINISH."""
    args = _args(comm_round=2)
    hub = LoopbackHub()
    server = FedML_Horizontal(args, 0, 2, backend="LOOPBACK", hub=hub)
    server.register_message_receive_handlers()
    server.start()
    server.receive_message(MyMessage.MSG_TYPE_C2S_CLIENT_STATUS, _online(1))
    server.receive_message(MyMessage.MSG_TYPE_C2S_CLIENT_STATUS, _online(2))
    assert server.is_initialized
    before = _drain(hub.register(1))
    assert [m.get_type() for m in before] == [
        MyMessage.MSG_TYPE_S2C_CHECK_CLIENT_STATUS,
        MyMessage.MSG_TYPE_S2C_INIT_CONFIG,
    ]
    # mid-round restart: the client lost its state and announces again
    server.receive_message(MyMessage.MSG_TYPE_C2S_CLIENT_STATUS, _online(1))
    rejoin = _drain(hub.register(1))
    assert [m.get_type() for m in rejoin] == [
        MyMessage.MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT]
    assert rejoin[0].get(MyMessage.MSG_ARG_KEY_ROUND_INDEX) == 0
    assert rejoin[0].get(MyMessage.MSG_ARG_KEY_MODEL_PARAMS) is not None
    # once its upload is in, a further ONLINE is a no-op (nothing to redo)
    server.receive_message(MyMessage.MSG_TYPE_C2S_SEND_MODEL_TO_SERVER,
                           _upload(server, 1))
    server.receive_message(MyMessage.MSG_TYPE_C2S_CLIENT_STATUS, _online(1))
    assert _drain(hub.register(1)) == []


def test_chaos_handshake_deadline_drops_silent_clients():
    """The all-online barrier must not wait forever: after the handshake
    deadline the cohort is re-selected from whoever reported ONLINE."""
    args = _args(handshake_timeout=0.3, min_clients_per_round=1)
    hub = LoopbackHub()
    server = FedML_Horizontal(args, 0, 2, backend="LOOPBACK", hub=hub)
    server.register_message_receive_handlers()
    server.start()
    server.receive_message(MyMessage.MSG_TYPE_C2S_CLIENT_STATUS, _online(1))
    assert not server.is_initialized  # client 2 still silent
    assert _wait_for(lambda: server.is_initialized, timeout=10.0)
    assert server.client_id_list_in_this_round == [1]
    assert len(server.data_silo_index_list) == 1
    types_1 = [m.get_type() for m in _drain(hub.register(1))]
    assert MyMessage.MSG_TYPE_S2C_INIT_CONFIG in types_1
    # the silent client only ever saw status probes — never an INIT
    types_2 = {m.get_type() for m in _drain(hub.register(2))}
    assert types_2 == {MyMessage.MSG_TYPE_S2C_CHECK_CLIENT_STATUS}


def test_chaos_handshake_deadline_reprobes_below_min_clients():
    """Below min_clients the deadline must NOT start the round — it re-probes
    the silent clients and re-arms instead."""
    args = _args(handshake_timeout=0.2, min_clients_per_round=2)
    hub = LoopbackHub()
    server = FedML_Horizontal(args, 0, 2, backend="LOOPBACK", hub=hub)
    server.register_message_receive_handlers()
    server.start()
    server.receive_message(MyMessage.MSG_TYPE_C2S_CLIENT_STATUS, _online(1))
    probes = hub.register(2)
    baseline = probes.qsize()  # the initial CHECK
    assert _wait_for(lambda: probes.qsize() > baseline, timeout=10.0)
    assert not server.is_initialized
    # the missing client finally answers: the normal barrier fires
    server.receive_message(MyMessage.MSG_TYPE_C2S_CLIENT_STATUS, _online(2))
    assert server.is_initialized
    server._arm_handshake_timer()  # no-op once initialized — nothing re-arms


# --- round-timeout extend path (satellite) -----------------------------------


def test_chaos_round_timeout_extends_below_min_then_closes():
    """Timeout with fewer than min_clients uploads must extend the round
    (re-arming the timer and re-offering the model to silent clients), then
    close normally once the threshold is met."""
    args = _args(round_timeout=0.3, min_clients_per_round=2)
    hub = LoopbackHub()
    server = FedML_Horizontal(args, 0, 2, backend="LOOPBACK", hub=hub)
    server.register_message_receive_handlers()
    server.start()
    server.receive_message(MyMessage.MSG_TYPE_C2S_CLIENT_STATUS, _online(1))
    server.receive_message(MyMessage.MSG_TYPE_C2S_CLIENT_STATUS, _online(2))
    q1, q2 = hub.register(1), hub.register(2)
    _drain(q1), _drain(q2)  # CHECK + INIT for both

    server.receive_message(MyMessage.MSG_TYPE_C2S_SEND_MODEL_TO_SERVER,
                           _upload(server, 1))
    # 1/2 uploads < min 2: the deadline extends instead of closing
    assert _wait_for(lambda: q2.qsize() > 0, timeout=10.0)
    assert server.history == []  # round still open
    assert server._timer is not None  # timer re-armed
    resent = _drain(q2)
    assert {m.get_type() for m in resent} == {
        MyMessage.MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT}
    assert resent[0].get(MyMessage.MSG_ARG_KEY_ROUND_INDEX) == 0
    assert _drain(q1) == []  # the client that already uploaded gets nothing

    # threshold met -> the round closes (and, at comm_round=1, finishes)
    server.receive_message(MyMessage.MSG_TYPE_C2S_SEND_MODEL_TO_SERVER,
                           _upload(server, 2))
    assert len(server.history) == 1
    finish_types = [m.get_type() for m in _drain(q1)]
    assert finish_types == [MyMessage.MSG_TYPE_S2C_FINISH]


# --- byte parity with faults disabled ----------------------------------------


class RecordingHub(LoopbackHub):
    """Loopback hub that keeps a per-rank multiset of every payload posted —
    the transcript two runs are compared by."""

    def __init__(self):
        super().__init__()
        self.posted = collections.defaultdict(collections.Counter)

    def post(self, rank, data):
        if data is not None:
            self.posted[rank][bytes(data)] += 1
        super().post(rank, data)


def _recorded_run(**extra):
    # telemetry off: trace stamps are uuid-random and would (correctly)
    # differ between otherwise-identical runs
    args = _args(comm_round=2, telemetry_enabled=False, **extra)
    hub = RecordingHub()
    server = FedML_Horizontal(args, 0, 2, backend="LOOPBACK", hub=hub)
    clients = [FedML_Horizontal(args, rank, 2, backend="LOOPBACK", hub=hub)
               for rank in (1, 2)]
    threads = [threading.Thread(target=c.run, daemon=True) for c in clients]
    for t in threads:
        t.start()
    server.start()
    server.run()
    for t in threads:
        t.join(timeout=60)
        assert not t.is_alive()
    assert len(server.history) == 2
    return {rank: dict(c) for rank, c in hub.posted.items()}


def test_chaos_disabled_fault_config_is_byte_identical():
    """`fault_*` keys present but zero/unset must leave the message flow
    byte-identical to a config without them (acceptance criterion: disabled
    chaos is not a behavior change)."""
    disabled = dict(fault_seed=11, fault_drop_rate=0.0,
                    fault_fail_send_rate=0.0, fault_delay_rate=0.0)
    assert FaultPlan.from_args(_args(**disabled)) is None  # no wrapper at all
    baseline = _recorded_run()
    with_keys = _recorded_run(**disabled)
    assert baseline == with_keys


def test_chaos_sync_duplicate_upload_commits_once():
    """A client that re-sends its round upload (it rejoined mid-round after
    already sending) must not advance the barrier or double-count in the
    fold — slot-keyed uploads make duplicates structurally idempotent."""
    args = _args(comm_round=1)
    hub = LoopbackHub()
    server = FedML_Horizontal(args, 0, 2, backend="LOOPBACK", hub=hub)
    server.register_message_receive_handlers()
    server.start()
    server.receive_message(MyMessage.MSG_TYPE_C2S_CLIENT_STATUS, _online(1))
    server.receive_message(MyMessage.MSG_TYPE_C2S_CLIENT_STATUS, _online(2))
    server.receive_message(MyMessage.MSG_TYPE_C2S_SEND_MODEL_TO_SERVER,
                           _upload(server, 1))
    assert server.history == []  # round open, waiting on client 2
    server.receive_message(MyMessage.MSG_TYPE_C2S_SEND_MODEL_TO_SERVER,
                           _upload(server, 1))  # duplicate
    assert server.history == []  # the duplicate must NOT close the barrier
    assert server.aggregator.received_count == 1
    server.receive_message(MyMessage.MSG_TYPE_C2S_SEND_MODEL_TO_SERVER,
                           _upload(server, 2))
    assert len(server.history) == 1
    # a post-commit re-send of the same round is stale and ignored
    server.receive_message(MyMessage.MSG_TYPE_C2S_SEND_MODEL_TO_SERVER,
                           _upload(server, 1))
    assert len(server.history) == 1


def test_chaos_async_server_restart_no_duplicate_commits(tmp_path):
    """Async (FedBuff-style) server dies mid-run and restarts from the
    round-state checkpoint while its free-running clients keep going. The
    in-flight uploads that raced the crash are replayed to the fresh
    incarnation AND re-sent by the rejoining clients — the per-sender
    sequence numbers resumed from the checkpoint must commit every update
    exactly once, and the version log must stay retention-bounded."""
    cfg = dict(
        dataset="mnist", model="lr", debug_small_data=True,
        client_num_in_total=2, client_num_per_round=2, comm_round=4,
        learning_rate=0.1, epochs=1, batch_size=8, frequency_of_the_test=1,
        random_seed=0, async_mode=True, async_buffer_size=2,
        round_ckpt_path=str(tmp_path / "round_state.msgpack"),
        ckpt_every_rounds=1, round_store_keep_versions=2,
    )
    # phase 1: the incarnation that dies once it touches version-2 traffic —
    # after at least one commit is checkpointed, before the run finishes.
    args_a = fedml_tpu.init(config={**cfg, "fault_crash_rank": 0,
                                    "fault_crash_at_round": 2})
    hub = LoopbackHub()
    server_a = FedML_Horizontal(args_a, 0, 2, backend="LOOPBACK", hub=hub)
    clients = [FedML_Horizontal(args_a, rank, 2, backend="LOOPBACK", hub=hub)
               for rank in (1, 2)]
    client_threads = [threading.Thread(target=c.run, daemon=True)
                      for c in clients]
    for t in client_threads:
        t.start()
    server_a.start()
    thread_a = threading.Thread(target=server_a.run, daemon=True)
    thread_a.start()
    thread_a.join(timeout=60)
    assert not thread_a.is_alive(), "crashed server's loop did not exit"
    assert server_a.com_manager.crashed
    assert 1 <= server_a.model_version < 4  # died mid-run, post-commit

    # phase 2: fresh incarnation on the same hub + checkpoint. The dead
    # server's queue holds the uploads that raced the crash — replay them
    # (real transports redeliver; the rejoining clients will ALSO re-send
    # theirs after the resumed INIT, so both duplicate paths are exercised).
    stale = hub.register(0)
    in_flight = []
    while not stale.empty():
        data = stale.get_nowait()
        if data is not None:
            m = Message.from_bytes(data)
            if m.get_type() == MyMessage.MSG_TYPE_C2S_SEND_MODEL_TO_SERVER:
                in_flight.append(data)
    args_b = fedml_tpu.init(config=cfg)
    server_b = FedML_Horizontal(args_b, 0, 2, backend="LOOPBACK", hub=hub)
    assert server_b.model_version == server_a.model_version  # resumed
    assert server_b.committed_updates == 2 * server_a.model_version
    for data in in_flight:
        hub.post(0, data)
    thread_b = threading.Thread(target=server_b.run, daemon=True)
    thread_b.start()
    server_b.start()  # re-probes; the still-running clients answer ONLINE
    thread_b.join(timeout=90)
    assert not thread_b.is_alive(), "resumed server did not finish"
    for t in client_threads:
        t.join(timeout=10)
        assert not t.is_alive()

    # exactly-once across both incarnations: 4 commits of K=2, no update
    # lost to the crash and none committed twice despite the replays
    assert server_b.model_version == 4
    assert server_b.committed_updates == 8
    assert server_b.shed_updates == 0
    # every commit folded exactly K updates (a free-running client may land
    # two consecutive sequences in one commit — that is not a duplicate;
    # exactly-once is per (sender, sequence), pinned by the totals above)
    assert all(e[1] == 2 and len(e[2]) == 2 for e in server_b._version_log)
    # retention: the log carries only the last keep_versions commits
    assert [e[0] for e in server_b._version_log] == [3, 4]


# --- hierarchical-federation drills (leaf crash / partition) ------------------


def test_tier_drill_leaf_crash_exactly_once():
    """A leaf aggregator killed mid-generation (shard persisted, upload
    lost): the root must rehydrate the dead leaf's chunk, every client's
    update commits exactly once, and the final model matches the fault-free
    reference within the accuracy gate."""
    from fedml_tpu.cross_silo.chaos import run_tier_drill

    result = run_tier_drill(scenario="leaf_crash")
    assert result.ok, result.summary()
    assert result.failovers == 1
    assert result.rehydrations == 1
    assert result.duplicate_commits == 0
    assert result.committed_updates == result.expected_updates
    rec = result.json_record()
    assert rec["ok"] and rec["scenario"] == "leaf_crash"


def test_tier_drill_partition_heals():
    """A root<->leaf cut for one round window: the orphaned chunk recomputes
    on a survivor (no shard store in this drill), the cut heals after the
    window, and the exactly-once + accuracy gates hold."""
    from fedml_tpu.cross_silo.chaos import run_tier_drill

    result = run_tier_drill(scenario="partition")
    assert result.ok, result.summary()
    assert result.failovers == 1
    assert result.rehydrations == 0  # no shard dir -> recompute path
    assert result.duplicate_commits == 0
    assert result.faults_injected.get("partition", 0) >= 1


# --- version-log retention boundary (tiered plane, satellite) ----------------


def test_tier_version_log_retention_resume_is_bit_exact(tmp_path):
    """Resume a tiered run from a checkpoint taken PAST the version-log
    retention boundary (more commits than keep_versions): the resumed run
    must finish bit-identical to an uninterrupted one, and the trimmed log
    must keep exactly the last-N window through the restart."""
    import jax

    from fedml_tpu.simulation.federation import build_tiered_simulator

    cfg = dict(
        dataset="mnist", model="lr", debug_small_data=True,
        client_num_in_total=6, client_num_per_round=4, comm_round=5,
        learning_rate=0.05, epochs=1, batch_size=8, frequency_of_the_test=1,
        random_seed=0, hier_num_leaves=2, group_comm_round=2,
        round_store_keep_versions=2,
    )
    ref, _ = build_tiered_simulator(fedml_tpu.init(config=cfg))
    ref.run(None, log_fn=None)
    assert [e[0] for e in ref.state.version_log] == [4, 5]  # trimmed to 2

    ckpt = str(tmp_path / "tier_state.msgpack")
    part, _ = build_tiered_simulator(fedml_tpu.init(
        config={**cfg, "comm_round": 3, "round_ckpt_path": ckpt}))
    part.run(None, log_fn=None)
    # 3 commits > keep 2: the checkpointed log already lost version 1
    assert [e[0] for e in part.state.version_log] == [2, 3]

    resumed, _ = build_tiered_simulator(fedml_tpu.init(
        config={**cfg, "round_ckpt_path": ckpt}))
    assert resumed.state.start_round == 3
    assert resumed.state.model_version == 3
    assert [e[0] for e in resumed.state.version_log] == [2, 3]
    resumed.run(None, log_fn=None)
    assert [e[0] for e in resumed.state.version_log] == [4, 5]

    for a, b in zip(jax.tree_util.tree_leaves(jax.device_get(ref.params)),
                    jax.tree_util.tree_leaves(
                        jax.device_get(resumed.params))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_straggler_drill_gates_goodput_and_accuracy():
    """The buffered-async straggler drill (PR 14 acceptance): under 10×
    seeded heavy-tail skew the async engine's goodput (committed updates
    per virtual second) must beat the synchronous round rate ≥3× with
    final accuracy within 2% of the sync run — and the drill's json_record
    must carry the gate verdicts for the bench artifact."""
    from fedml_tpu.cross_silo.chaos import run_straggler_drill

    result = run_straggler_drill()
    assert result.ok, result.summary()
    assert result.goodput_ratio >= 3.0
    assert abs(result.acc_delta) <= 0.02
    rec = result.json_record()
    assert rec["ok"] and rec["goodput_ratio"] >= 3.0
