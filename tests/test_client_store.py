"""Client-state arena + sharded cohort axis: parity, spill, scale.

The arena (simulation/client_store.py) replaces the legacy per-client dict
with fixed-capacity stacked device buffers behind a ``client_id → slot``
map. Everything here is a parity claim against the dict path it replaced —
same metrics, same params, same per-client states, bit-for-bit — plus the
scaling properties that motivated it: one jitted gather/scatter per round,
LRU spill past capacity, a mesh-sharded cohort axis, and a 1k-client round
that completes inside a tier-1 wall-clock budget.
"""

import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import fedml_tpu
from fedml_tpu.data.federated import ArrayPair, build_federated_data
from fedml_tpu.parallel.mesh import AXIS_CLIENT, MeshConfig, create_mesh
from fedml_tpu.simulation import build_simulator
from fedml_tpu.simulation.client_store import ClientStateArena, cohort_local_update
from fedml_tpu.simulation.sampling import sample_clients

TIMING_KEYS = {"round_time", "dispatch_time", "pack_time", "pack_wait",
               "overlap", "phases"}


def _args(**kw):
    base = dict(
        dataset="mnist", model="lr", debug_small_data=True,
        client_num_in_total=12, client_num_per_round=4, comm_round=3,
        learning_rate=0.1, epochs=1, batch_size=32,
        frequency_of_the_test=2, random_seed=0,
        partition_method="hetero", partition_alpha=0.5,
        federated_optimizer="SCAFFOLD",
    )
    base.update(kw)
    return fedml_tpu.init(config=base)


def _run(**kw):
    sim, apply_fn = build_simulator(_args(**kw))
    hist = sim.run(apply_fn, log_fn=None)
    return sim, hist


def _strip_timing(hist):
    return [{k: v for k, v in rec.items() if k not in TIMING_KEYS}
            for rec in hist]


def _assert_tree_equal(a, b):
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def _mesh2():
    return create_mesh(MeshConfig(axes=((AXIS_CLIENT, 2),)),
                       devices=jax.devices()[:2])


# --- the shared cohort vmap -------------------------------------------------


def test_cohort_local_update_matches_raw_vmap():
    def local_update(params, state, batch, rng):
        return params * batch["x"].sum() + state + jax.random.uniform(rng)

    params = jnp.asarray(2.0)
    states = jnp.arange(4, dtype=jnp.float32)
    cohort = {"x": jnp.arange(8, dtype=jnp.float32).reshape(4, 2)}
    rngs = jax.random.split(jax.random.PRNGKey(0), 4)
    got = cohort_local_update(local_update, params, states, cohort, rngs)
    want = jax.vmap(local_update, in_axes=(None, 0, 0, 0))(
        params, states, cohort, rngs)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    # stacked params / shared state (the hierarchical/decentralized shape)
    sp = jnp.arange(4, dtype=jnp.float32)
    got2 = cohort_local_update(local_update, sp, jnp.asarray(0.5), cohort,
                               rngs, params_axis=0, state_axis=None)
    want2 = jax.vmap(local_update, in_axes=(0, None, 0, 0))(
        sp, jnp.asarray(0.5), cohort, rngs)
    np.testing.assert_array_equal(np.asarray(got2), np.asarray(want2))


# --- arena vs dict: bit-exact parity ----------------------------------------


def test_arena_matches_dict_backend_bit_exact():
    """Same history, params, and per-client states as the dict path, to the
    bit — the arena is a storage layout change, not a numeric one."""
    sim_a, hist_a = _run()
    sim_d, hist_d = _run(client_state_backend="dict")
    assert sim_a._arena is not None and sim_d._arena is None
    assert _strip_timing(hist_a) == _strip_timing(hist_d)
    _assert_tree_equal(sim_a.params, sim_d.params)
    assert sim_d.client_states  # SCAFFOLD is stateful — dict path populated
    for cid, st in sim_d.client_states.items():
        _assert_tree_equal(sim_a._arena.state_of(cid), st)


def test_arena_spill_and_reload_bit_exact(tmp_path):
    """Capacity below the touched-client count forces LRU eviction to host
    RAM and (host_capacity == capacity) to msgpack files; resampled clients
    reload through both tiers with no numeric trace."""
    sim_d, hist_d = _run(client_state_backend="dict", comm_round=6)
    sim_a, hist_a = _run(comm_round=6, client_state_capacity=5,
                         client_state_spill_dir=str(tmp_path / "spill"))
    arena = sim_a._arena
    assert arena.capacity == 5
    assert arena.spilled_count > 0, "run never exercised the spill tier"
    assert _strip_timing(hist_a) == _strip_timing(hist_d)
    _assert_tree_equal(sim_a.params, sim_d.params)
    for cid, st in sim_d.client_states.items():
        _assert_tree_equal(arena.state_of(cid), st)


def test_arena_reload_actually_round_trips(tmp_path):
    """Unit-level spill/reload: scatter distinct rows through a 2-slot
    arena, then read every client back — including ones that went through
    the disk tier."""
    proto = {"a": jnp.zeros((3,)), "b": jnp.zeros(())}
    arena = ClientStateArena(proto, 2, spill_dir=str(tmp_path),
                             host_capacity=2)
    for cid in range(6):
        arena.gather([cid])
        arena.scatter([cid], {"a": jnp.full((1, 3), float(cid)),
                              "b": jnp.asarray([float(cid) * 10])})
    assert arena.spilled_count == 4
    for cid in range(6):
        st = arena.state_of(cid)
        np.testing.assert_array_equal(np.asarray(st["a"]), np.full(3, cid))
        np.testing.assert_array_equal(np.asarray(st["b"]), cid * 10)
    # batched re-gather of the two disk-tier clients (0 and 1 are the LRU
    # victims pushed past host_capacity) loads them back in one scatter
    stacked = arena.gather([0, 1])
    np.testing.assert_array_equal(
        np.asarray(stacked["b"]), np.asarray([0.0, 10.0]))
    # an oversize cohort is a hard error, not silent thrash
    with pytest.raises(ValueError, match="slots"):
        arena.gather(list(range(6)))


def test_arena_discard_reclaims_every_tier_including_stale_files(tmp_path):
    """Permanent departure (cross-device churn) must not leak: the slot,
    the host row, the live spill file, AND the stale-but-inert file left
    behind when a disk-tier client was merely read back all go away."""
    proto = {"a": jnp.zeros((3,))}
    arena = ClientStateArena(proto, 2, spill_dir=str(tmp_path),
                             host_capacity=2)
    for cid in range(6):
        arena.gather([cid])
        arena.scatter([cid], {"a": jnp.full((1, 3), float(cid))})
    # reading client 0 back promotes it to a device slot but deliberately
    # leaves its file on disk (inert — _on_disk is the source of truth)
    arena.gather([0])
    files = lambda: sorted(p.name for p in tmp_path.glob("client_*.msgpack"))
    assert "client_0.msgpack" in files()
    before = arena.spilled_count
    # clients 0 (resident again, stale file), 1 and 2 (disk tier) depart;
    # duplicate and never-seen ids are harmless
    reclaimed = arena.discard([0, 1, 2, 2, 99])
    assert reclaimed == 3          # 0's stale file + 1's and 2's live files
    assert files() == []           # every file for the departed is gone
    assert arena.spilled_count < before
    # departed clients are fully forgotten: they read back as fresh proto
    for cid in (0, 1, 2):
        np.testing.assert_array_equal(
            np.asarray(arena.state_of(cid)["a"]), np.zeros(3))
    # survivors are untouched across all tiers
    for cid in (3, 4, 5):
        np.testing.assert_array_equal(
            np.asarray(arena.state_of(cid)["a"]), np.full(3, cid))


def test_arena_checkpoint_resume_bit_exact(tmp_path):
    """Interrupted-at-2 resume == uninterrupted run: the checkpoint carries
    the whole arena (slots, map, clock, spilled rows)."""
    kw = dict(comm_round=4, frequency_of_the_test=100)
    sim_full, _ = _run(**kw)
    ck = str(tmp_path / "ck")
    _run(**dict(kw, comm_round=2, checkpoint_dir=ck, checkpoint_frequency=1))
    sim_res, hist_res = _run(**dict(kw, checkpoint_dir=ck,
                                    checkpoint_frequency=1))
    assert hist_res[0]["round"] == 2
    _assert_tree_equal(sim_full.params, sim_res.params)
    for cid in range(12):
        _assert_tree_equal(sim_full._arena.state_of(cid),
                           sim_res._arena.state_of(cid))


def test_arena_capacity_below_cohort_rejected():
    with pytest.raises(ValueError, match="client_state_capacity"):
        build_simulator(_args(client_state_capacity=3))


def test_arena_watchdog_plus_disk_spill_rejected():
    with pytest.raises(ValueError, match="watchdog"):
        build_simulator(_args(client_state_spill_dir="/tmp/never",
                              watchdog_factor=3.0))


def test_arena_unknown_backend_rejected():
    with pytest.raises(ValueError, match="client_state_backend"):
        build_simulator(_args(client_state_backend="redis"))


def test_arena_selfheal_rollback_parity():
    """The watchdog snapshot/restore covers the arena: a run under the
    watchdog (no rollbacks triggered at sane thresholds) matches dict."""
    kw = dict(watchdog_factor=100.0, comm_round=3)
    sim_a, hist_a = _run(**kw)
    sim_d, hist_d = _run(client_state_backend="dict", **kw)
    assert _strip_timing(hist_a) == _strip_timing(hist_d)
    _assert_tree_equal(sim_a.params, sim_d.params)


# --- mesh-sharded cohort axis -----------------------------------------------


def test_mesh_history_bit_identical_and_never_unsharded():
    """2-device client mesh: bit-identical round history to the unsharded
    run, and the stacked update entering aggregation is asserted (via
    sharding inspection inside the compiled step) to never materialize
    unsharded."""
    sim1, hist1 = _run()
    seen = {}
    mesh = _mesh2()
    sim2, apply_fn = build_simulator(_args(), mesh=mesh)
    sim2._sharding_probe = lambda tag, s: seen.setdefault(tag, s)
    hist2 = sim2.run(apply_fn, log_fn=None)
    assert not seen["update"].is_fully_replicated, \
        "stacked update materialized unsharded inside the round step"
    assert seen["agg"].is_fully_replicated
    assert _strip_timing(hist1) == _strip_timing(hist2)
    # params agree to cross-device reduction-order noise (the mesh run
    # reduces per-shard then combines; same tolerance class as the
    # pre-arena mesh path)
    for a, b in zip(jax.tree.leaves(sim1.params), jax.tree.leaves(sim2.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)


def test_mesh_padded_cohort_matches_unsharded():
    """per_round=5 on a 2-device mesh pads the cohort to 6; the padded row
    carries zero weight and a duplicated id, so results match the unsharded
    5-client run."""
    kw = dict(client_num_per_round=5, federated_optimizer="FedAvg")
    _, hist1 = _run(**kw)
    sim2, apply_fn = build_simulator(_args(**kw), mesh=_mesh2())
    assert sim2._cohort_pad == 1
    hist2 = sim2.run(apply_fn, log_fn=None)
    for r1, r2 in zip(hist1, hist2):
        for k in r1:
            if k in TIMING_KEYS:
                continue
            if isinstance(r1[k], float):
                assert abs(r1[k] - r2[k]) < 1e-5, (k, r1[k], r2[k])
            else:
                assert r1[k] == r2[k], (k, r1[k], r2[k])


def test_mesh_padding_with_attack_rejected():
    """Padded rows entering a custom update transform would corrupt it —
    the combination must refuse at build time, not silently mis-aggregate."""
    with pytest.raises(ValueError, match="padding|multiple"):
        build_simulator(
            _args(client_num_per_round=5, federated_optimizer="FedAvg",
                  attack_type="scale"),
            mesh=_mesh2())


# --- pure per-round sampling ------------------------------------------------


def test_sample_clients_pure_and_deterministic():
    before = np.random.get_state()
    a = sample_clients(7, 3, 1000, 10)
    after = np.random.get_state()
    # no draw from (or reseed of) the process-global stream
    assert before[0] == after[0]
    np.testing.assert_array_equal(before[1], after[1])
    assert before[2:] == after[2:]
    np.testing.assert_array_equal(a, sample_clients(7, 3, 1000, 10))
    assert len(np.unique(a)) == 10 and a.max() < 1000
    # distinct rounds and seeds draw distinct cohorts
    assert not np.array_equal(a, sample_clients(7, 4, 1000, 10))
    assert not np.array_equal(a, sample_clients(8, 3, 1000, 10))
    np.testing.assert_array_equal(
        sample_clients(7, 0, 10, 10), np.arange(10))


# --- scale smoke ------------------------------------------------------------


def test_thousand_client_round_under_budget():
    """1000-client sampled SCAFFOLD round (arena gather → vmap → sharded-
    style aggregation → scatter) completes — compile included — inside a
    tier-1 budget."""
    pool, spc, dim = 2000, 8, 16
    rng = np.random.default_rng(0)
    n = pool * spc
    y = (np.arange(n) % 2).astype(np.int64)
    x = rng.normal(size=(n, dim)).astype(np.float32) \
        + 2.0 * y[:, None].astype(np.float32)
    net_map = {c: list(range(c * spc, (c + 1) * spc)) for c in range(pool)}
    fed = build_federated_data(
        ArrayPair(x, y), ArrayPair(x[:64], y[:64]), net_map, 2)
    args = _args(client_num_in_total=pool, client_num_per_round=1000,
                 comm_round=1, batch_size=spc, frequency_of_the_test=100,
                 dataset="synthetic_blobs")
    t0 = time.perf_counter()
    sim, _ = build_simulator(args, fed_data=fed)
    assert sim._arena is not None
    hist = sim.run(apply_fn=None, log_fn=None)
    wall = time.perf_counter() - t0
    assert len(hist) == 1 and np.isfinite(hist[0]["train_loss"])
    assert sim._arena.resident_count == 1000
    assert wall < 60.0, f"1k-client round took {wall:.1f}s (budget 60s)"
