"""FedCV object detection: federated grid detector learns real localization.

Reference app/fedcv/object_detection (YOLOv5 federated); here the compact
anchor-free grid detector + detection loss ride the shared engine, and the
test scores IoU-matched detections — not just loss descent.
"""

import numpy as np

import jax
import jax.numpy as jnp

import fedml_tpu
from fedml_tpu.algorithms.fedcv_detection import get_detection_algorithm
from fedml_tpu.models.detection import (
    GridDetector,
    box_iou,
    decode_boxes,
    rasterize_boxes,
)
from fedml_tpu.simulation.fed_sim import FedSimulator, SimConfig


def test_rasterize_decode_roundtrip():
    boxes = np.array([[0.25, 0.25, 0.2, 0.2], [0.75, 0.5, 0.3, 0.1]])
    classes = np.array([0, 1])
    t = rasterize_boxes(boxes, classes, grid=8, num_classes=2)
    assert t[..., 0].sum() == 2
    # a perfect prediction grid decodes back to the same boxes
    pred = np.zeros((8, 8, 7), np.float32)
    pred[..., 0] = -10.0
    for (cx, cy, w, h), c in zip(boxes, classes):
        gx, gy = int(cx * 8), int(cy * 8)
        pred[gy, gx, 0] = 10.0
        pred[gy, gx, 1] = cx * 8 - gx
        pred[gy, gx, 2] = cy * 8 - gy
        pred[gy, gx, 3] = np.log1p(w)
        pred[gy, gx, 4] = np.log1p(h)
        pred[gy, gx, 5 + c] = 5.0
    out_boxes, out_cls, _ = decode_boxes(pred)
    assert len(out_boxes) == 2
    for b, c in zip(boxes, classes):
        ious = [box_iou(b, ob) for ob in out_boxes]
        j = int(np.argmax(ious))
        assert ious[j] > 0.95
        assert out_cls[j] == c


def test_federated_detection_learns_localization():
    args = fedml_tpu.init(config=dict(
        dataset="object_detection", debug_small_data=True,
        client_num_in_total=4, client_num_per_round=4,
        partition_method="homo", random_seed=0))
    from fedml_tpu import data as data_mod

    fed, _ = data_mod.load(args)
    model = GridDetector(num_classes=2, width=16)

    def apply_fn(params, x, train=False, rngs=None):
        return model.apply(params, x, train=train)

    sample = fed.train_data_global.x[:1]
    variables = model.init(jax.random.PRNGKey(0), jnp.asarray(sample),
                           train=False)
    alg = get_detection_algorithm(apply_fn, lr=3e-3, epochs=2)
    sim = FedSimulator(
        fed, alg, variables,
        SimConfig(comm_round=12, client_num_in_total=4, client_num_per_round=4,
                  batch_size=16, frequency_of_the_test=1000),
    )
    hist = sim.run(apply_fn=None, log_fn=None)
    assert hist[-1]["train_loss"] < hist[0]["train_loss"]

    # IoU-matched detection quality on held-out images
    test = fed.test_data_global
    S = test.y.shape[1]
    preds = np.asarray(apply_fn(sim.params, jnp.asarray(test.x[:48])))
    matched, total = 0, 0
    for i in range(48):
        gt = test.y[i]
        ys, xs = np.nonzero(gt[..., 0] > 0)
        pb, pc, _ = decode_boxes(preds[i], obj_threshold=0.5)
        for y, x in zip(ys, xs):
            total += 1
            cx = (x + gt[y, x, 2]) / S
            cy = (y + gt[y, x, 3]) / S
            gt_box = np.array([cx, cy, gt[y, x, 4], gt[y, x, 5]])
            best = max((box_iou(gt_box, b) for b, c in zip(pb, pc)
                        if c == int(gt[y, x, 1])), default=0.0)
            if best >= 0.5:
                matched += 1
    recall = matched / max(total, 1)
    assert recall > 0.5, f"IoU>=0.5 class-matched recall {recall:.2f}"
