"""Torch/HF checkpoint import: logit equality + federated fine-tune.

VERDICT r2 missing #3: the reference's FedNLP path fine-tunes pretrained
HF BERT (app/fednlp/.../bert_model.py). Here a REAL HuggingFace
BertForSequenceClassification (config-constructed — zero egress) is saved
as a torch state_dict file, imported into the flax BERT, and the logits are
asserted equal to the torch forward; then a federated fine-tune run starts
from the imported weights and learns.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from fedml_tpu.models.bert import BertConfig, BertForSequenceClassification
from fedml_tpu.utils.torch_import import (
    convert_state_dict,
    import_bert_classifier,
    linear_kernel,
    load_torch_state_dict,
)

CFG = BertConfig(vocab_size=64, hidden_size=32, num_hidden_layers=2,
                 num_attention_heads=2, intermediate_size=64,
                 max_position_embeddings=16, type_vocab_size=2, num_labels=3)


def _hf_model():
    import transformers

    hf_cfg = transformers.BertConfig(
        vocab_size=CFG.vocab_size, hidden_size=CFG.hidden_size,
        num_hidden_layers=CFG.num_hidden_layers,
        num_attention_heads=CFG.num_attention_heads,
        intermediate_size=CFG.intermediate_size,
        max_position_embeddings=CFG.max_position_embeddings,
        type_vocab_size=CFG.type_vocab_size, num_labels=CFG.num_labels,
        hidden_act="gelu",
    )
    model = transformers.BertForSequenceClassification(hf_cfg)
    model.eval()
    return model


def test_hf_bert_checkpoint_logit_equality(tmp_path):
    import torch

    torch.manual_seed(0)
    hf = _hf_model()
    ckpt = str(tmp_path / "bert_tiny.pt")
    torch.save(hf.state_dict(), ckpt)

    variables = import_bert_classifier(ckpt, CFG)
    flax_model = BertForSequenceClassification(CFG)

    rng = np.random.default_rng(0)
    ids = rng.integers(0, CFG.vocab_size, size=(4, 12)).astype(np.int32)
    mask = np.ones((4, 12), np.float32)
    mask[2, 8:] = 0.0  # one padded sequence exercises the attention bias
    with torch.no_grad():
        torch_logits = hf(
            input_ids=torch.from_numpy(ids.astype(np.int64)),
            attention_mask=torch.from_numpy(mask.astype(np.int64)),
        ).logits.numpy()
    flax_logits = np.asarray(flax_model.apply(
        variables, jnp.asarray(ids), attention_mask=jnp.asarray(mask),
        train=False))
    np.testing.assert_allclose(flax_logits, torch_logits, atol=2e-5)


def test_import_shape_check_fails_loudly(tmp_path):
    import torch

    hf = _hf_model()
    sd = hf.state_dict()
    sd["classifier.weight"] = torch.zeros(5, 7)  # wrong shape
    with pytest.raises(ValueError, match="shape mismatch"):
        import_bert_classifier(
            {k: v.numpy() for k, v in sd.items()}, CFG)


def test_import_rejects_unmapped_and_missing_keys():
    with pytest.raises(ValueError, match="no mapping"):
        convert_state_dict({"surprise.weight": np.zeros((2, 2))},
                           mapping={}, expected_shapes=None)
    # a checkpoint that leaves flax leaves unpopulated is also rejected —
    # even when the mapping table covers them (e.g. encoder-only BERT)
    with pytest.raises(ValueError, match="not populated"):
        convert_state_dict(
            {"a.weight": np.zeros((2, 3))},
            mapping={"a.weight": (("a", "kernel"), linear_kernel),
                     "b.bias": (("b", "bias"), np.asarray)},
            expected_shapes={("a", "kernel"): (3, 2), ("b", "bias"): (4,)},
        )


def test_federated_finetune_from_imported_weights(tmp_path):
    """The reference fednlp flow: pretrained checkpoint -> federated
    fine-tune. Labels here are a function of the first token, so the tiny
    randomly-initialized 'pretrained' net must genuinely learn."""
    import torch

    from fedml_tpu.algorithms import LocalTrainConfig, get_algorithm
    from fedml_tpu.data.federated import ArrayPair, build_federated_data
    from fedml_tpu.simulation.fed_sim import FedSimulator, SimConfig

    torch.manual_seed(1)
    hf = _hf_model()
    ckpt = str(tmp_path / "pretrained.pt")
    torch.save(hf.state_dict(), ckpt)
    variables = import_bert_classifier(load_torch_state_dict(ckpt), CFG)

    rng = np.random.default_rng(0)
    n = 256
    x = rng.integers(0, CFG.vocab_size, size=(n, 12)).astype(np.int32)
    y = (x[:, 0] % CFG.num_labels).astype(np.int32)
    idx_map = {c: list(range(c * 64, (c + 1) * 64)) for c in range(4)}
    fed = build_federated_data(ArrayPair(x, y), ArrayPair(x[:64], y[:64]),
                               idx_map, CFG.num_labels)

    model = BertForSequenceClassification(CFG)

    def apply_fn(v, xx, train=False, rngs=None, mutable=False):
        return model.apply(v, xx, train=False)  # dropout off for the test

    alg = get_algorithm("FedAvg", apply_fn,
                        LocalTrainConfig(lr=1e-3, epochs=1,
                                         client_optimizer="adam"))
    sim = FedSimulator(fed, alg, variables,
                       SimConfig(comm_round=6, client_num_in_total=4,
                                 client_num_per_round=4, batch_size=16,
                                 frequency_of_the_test=1000, seed=0))
    hist = sim.run(apply_fn=None, log_fn=None)
    assert hist[-1]["train_loss"] < hist[0]["train_loss"], hist
    assert hist[-1]["train_acc"] > 0.75 > hist[0]["train_acc"], hist
