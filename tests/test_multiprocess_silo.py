"""Multi-process hierarchical silo e2e: real OS processes joined by
jax.distributed, one sharded local update spanning both (VERDICT #9;
reference ``client_slave_manager.py:39`` semantics).

Each worker gets 2 virtual CPU devices, so the silo mesh is 2 procs x 2
devices = 4-way data parallel across a genuine process boundary.
"""

import json
import os
import socket
import subprocess
import sys

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO_ROOT, "scripts", "run_hier_silo_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.slow
def test_two_process_silo_round(tmp_path):
    port = _free_port()
    procs = []
    outs = [str(tmp_path / f"out_{i}.json") for i in range(2)]
    for pid in range(2):
        env = dict(
            os.environ,
            PYTHONPATH=REPO_ROOT,
            JAX_PLATFORMS="cpu",
            PALLAS_AXON_POOL_IPS="",
            XLA_FLAGS="--xla_force_host_platform_device_count=2",
            JAX_COORDINATOR_ADDRESS=f"127.0.0.1:{port}",
            JAX_NUM_PROCESSES="2",
            JAX_PROCESS_ID=str(pid),
        )
        procs.append(subprocess.Popen(
            [sys.executable, WORKER, "--out", outs[pid], "--rounds", "2"],
            env=env, cwd=REPO_ROOT,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        ))
    logs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=420)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        logs.append(out)
    assert all(p.returncode == 0 for p in procs), "\n----\n".join(logs)

    master = json.load(open(outs[0]))
    slave = json.load(open(outs[1]))
    # both processes saw the full 4-device world (2 local each)
    assert master["global_devices"] == 4 and master["local_devices"] == 2
    assert slave["global_devices"] == 4 and slave["local_devices"] == 2
    assert slave["slave"] is True
    hist = master["history"]
    assert len(hist) == 2
    import numpy as np

    assert np.isfinite(hist[-1]["test_acc"])
