"""Extended loaders, poisoned/centralized modes, device mapping."""

import numpy as np
import pytest

import fedml_tpu
from fedml_tpu import data as data_mod
from fedml_tpu.device import load_device_mapping, mapping_for_rank, total_processes


@pytest.mark.parametrize("dataset,classes", [
    ("ILSVRC2012", 1000), ("gld23k", 203), ("stackoverflow_lr", 20),
    ("UCI", 2), ("lending_club_loan", 2), ("NUS_WIDE", 5), ("fets2021", 4),
])
def test_extended_loaders_shapes(dataset, classes):
    args = fedml_tpu.init(config=dict(
        dataset=dataset, debug_small_data=True, client_num_in_total=4,
        partition_method="homo", random_seed=0))
    fed, class_num = data_mod.load(args)
    assert class_num == classes
    assert fed.client_num == 4
    assert fed.train_data_num > 0 and fed.test_data_num > 0
    # tuple contract parity
    t = fed.to_tuple()
    assert len(t) == 8 and t[7] == classes


def test_centralized_mode_single_client():
    args = fedml_tpu.init(config=dict(
        dataset="mnist", debug_small_data=True, centralized=True,
        client_num_in_total=10, random_seed=0))
    fed, _ = data_mod.load(args)
    assert fed.client_num == 1
    assert fed.train_data_local_num_dict[0] == fed.train_data_num


def test_poisoned_clients_trigger_and_label():
    args = fedml_tpu.init(config=dict(
        dataset="mnist", debug_small_data=True, client_num_in_total=4,
        partition_method="homo", poison_ratio=0.5, poison_target_label=7,
        random_seed=0))
    fed, _ = data_mod.load(args)
    poisoned = [
        c for c, p in fed.train_data_local_dict.items()
        if (p.y == 7).all() and len(p.y) > 0
    ]
    assert len(poisoned) == 2


def test_device_mapping_yaml(tmp_path):
    f = tmp_path / "gpu_mapping.yaml"
    f.write_text("""
mapping_default:
  host1: [2, 1]
  host2: [1]
""")
    mapping = load_device_mapping(str(f))
    assert total_processes(mapping) == 4
    assert mapping_for_rank(mapping, 0) == [0]
    assert mapping_for_rank(mapping, 1) == [0]
    assert mapping_for_rank(mapping, 2) == [1]
    assert mapping_for_rank(mapping, 3) == [0]  # host2 slot 0
    with pytest.raises(ValueError):
        mapping_for_rank(mapping, 4)


def test_get_device_returns_jax_device():
    import jax

    d = fedml_tpu.device.get_device(None) if hasattr(fedml_tpu, "device") else None
    from fedml_tpu.device import get_device

    d = get_device(None)
    assert d in jax.devices()


def test_fednlp_text_classification_learns():
    from fedml_tpu.simulation import build_simulator

    args = fedml_tpu.init(config=dict(
        dataset="20news", model="transformer_classifier", vocab_size=256,
        max_seq_len=32, debug_small_data=True, client_num_in_total=6,
        client_num_per_round=6, comm_round=3, learning_rate=1e-3,
        client_optimizer="adam", batch_size=8, frequency_of_the_test=2,
        random_seed=0))
    sim, apply_fn = build_simulator(args)
    hist = sim.run(apply_fn, log_fn=None)
    assert hist[-1]["train_loss"] < hist[0]["train_loss"]
    assert hist[-1]["test_acc"] > 0.2  # 20 classes, random = 0.05


def test_fedgraphnn_gcn_learns():
    from fedml_tpu.simulation import build_simulator

    args = fedml_tpu.init(config=dict(
        dataset="moleculenet", model="gcn", debug_small_data=True,
        client_num_in_total=4, client_num_per_round=4, comm_round=8,
        partition_method="homo", learning_rate=0.01, client_optimizer="adam",
        epochs=2, batch_size=16, frequency_of_the_test=7, random_seed=0))
    sim, apply_fn = build_simulator(args)
    hist = sim.run(apply_fn, log_fn=None)
    assert hist[-1]["train_loss"] < hist[0]["train_loss"]
    assert hist[-1]["test_acc"] > 0.7  # structural label is easy for a GCN


def test_pack_clients_preserves_float_labels():
    """Float (regression) labels must not be truncated to ints by the native
    int32 fast path (ADVICE r1: data/federated.py)."""
    from fedml_tpu.data.federated import ArrayPair, build_federated_data

    rng = np.random.default_rng(0)
    x = rng.normal(size=(40, 3)).astype(np.float32)
    y = rng.random(40).astype(np.float32)  # values in (0, 1)
    fed = build_federated_data(
        ArrayPair(x, y), ArrayPair(x[:8], y[:8]),
        {0: list(range(20)), 1: list(range(20, 40))}, class_num=1,
    )
    batches = fed.pack_clients([0, 1], batch_size=8)
    got = batches.y[batches.mask.astype(bool)]
    assert got.dtype == np.float32
    # all true labels present, none floored to 0.0/1.0
    np.testing.assert_allclose(np.sort(got), np.sort(y), rtol=1e-6)


def test_pack_client_index_matches_pack_clients():
    """The index-only (device-resident) packer must reproduce pack_clients
    bit-for-bit under the same rng stream."""
    from fedml_tpu.data.federated import ArrayPair, build_federated_data

    rng = np.random.default_rng(1)
    x = rng.normal(size=(50, 4)).astype(np.float32)
    y = rng.integers(0, 5, 50).astype(np.int32)
    fed = build_federated_data(
        ArrayPair(x, y), ArrayPair(x[:8], y[:8]),
        {0: list(range(13)), 1: list(range(13, 50))}, class_num=5,
    )
    dense = fed.pack_clients([1, 0], batch_size=8, num_batches=5,
                             rng=np.random.default_rng([7, 3]))
    idx = fed.pack_client_index([1, 0], batch_size=8, num_batches=5,
                                rng=np.random.default_rng([7, 3]))
    np.testing.assert_array_equal(idx.mask, dense.mask)
    np.testing.assert_array_equal(idx.num_samples, dense.num_samples)
    gx = x[idx.idx] * idx.mask[..., None]
    np.testing.assert_array_equal(gx, dense.x * dense.mask[..., None])
    gy = y[idx.idx] * idx.mask.astype(np.int32)
    np.testing.assert_array_equal(gy, dense.y * dense.mask.astype(np.int32))
