"""L6 app-ecosystem task families (reference app/ tree):

- FedNLP: sequence tagging, span extraction, seq2seq (app/fednlp/*)
- FedGraphNN: node classification, link prediction, graph regression
  (app/fedgraphnn/*)

Each runs a few federated rounds through the shared simulator and must LEARN
(beat the task's chance level by a margin), not just execute.
"""

import numpy as np
import pytest

import fedml_tpu
from fedml_tpu.simulation import build_simulator


def _run(config, rounds=4):
    base = dict(
        debug_small_data=True, client_num_in_total=4, client_num_per_round=4,
        comm_round=rounds, epochs=2, batch_size=16,
        frequency_of_the_test=rounds, random_seed=0,
        partition_method="homo",
    )
    base.update(config)
    args = fedml_tpu.init(config=base)
    sim, apply_fn = build_simulator(args)
    hist = sim.run(apply_fn, log_fn=None)
    return hist


def test_fednlp_seq_tagging_learns():
    hist = _run(dict(
        dataset="seq_tagging", model="transformer_tagger",
        vocab_size=128, max_seq_len=64, model_dim=64, model_layers=1,
        model_heads=4, learning_rate=0.01, client_optimizer="adam",
        epochs=4,
    ), rounds=10)
    # 9 tags -> chance ~0.11; the contextual rule is learnable fast
    assert hist[-1]["test_acc"] > 0.6, hist[-1]


def test_fednlp_span_extraction_learns():
    hist = _run(dict(
        dataset="span_extraction", model="span_extractor",
        vocab_size=256, max_seq_len=64, model_dim=64, model_layers=2,
        model_heads=4, learning_rate=0.003, client_optimizer="adam",
        batch_size=32, epochs=3,
    ), rounds=8)
    # chance = 1/seq_len ~ 0.016 per boundary; the bracketing delimiters
    # make both boundaries learnable (reaches ~0.97)
    assert hist[-1]["test_acc"] > 0.7, hist[-1]


def test_fednlp_seq2seq_learns():
    hist = _run(dict(
        dataset="seq2seq", model="seq2seq",
        vocab_size=64, src_seq_len=16, tgt_seq_len=8,
        model_dim=64, model_layers=2, model_heads=4,
        learning_rate=0.003, client_optimizer="adam", epochs=6,
    ), rounds=15)
    # per-token chance ~1/63; reversal needs encoder-decoder attention
    # (reaches 1.0)
    assert hist[-1]["test_acc"] > 0.8, hist[-1]


def test_fedgraphnn_node_classification_learns():
    hist = _run(dict(
        dataset="ego_networks_node_clf", model="gcn_node",
        learning_rate=0.003, client_optimizer="adam", epochs=6,
    ), rounds=15)
    # 2-class per-node, balanced-ish by construction -> chance ~0.5
    assert hist[-1]["test_acc"] > 0.6, hist[-1]


def test_fedgraphnn_link_prediction_learns():
    hist = _run(dict(
        dataset="ego_networks_link_pred", model="gcn_link",
        learning_rate=0.003, client_optimizer="adam", epochs=6,
    ), rounds=16)
    # pairwise 2-class; community structure + observed edges make links
    # recoverable above the ~0.66 majority (no-link) rate
    assert hist[-1]["test_acc"] > 0.7, hist[-1]


def test_fedgraphnn_graph_regression_learns():
    hist = _run(dict(
        dataset="moleculenet_reg", model="gcn_reg",
        learning_rate=0.003, client_optimizer="adam", epochs=3,
    ), rounds=8)
    # loss_kind=mse engages via the model name; test_loss is an MSE here.
    # Targets span [0, 4]; predicting the mean gives MSE ~1.3 — structure
    # must cut it well below that.
    assert hist[-1]["test_loss"] < 0.4, hist[-1]
    # and the within-0.5 hit rate ("accuracy") should be high
    assert hist[-1]["test_acc"] > 0.6, hist[-1]


@pytest.mark.slow
def test_medical_chest_xray_classification_learns():
    """Chest-x-ray classification (reference app/fedcv/
    medical_chest_xray_image_clf: DenseNet + CE over CheXpert/NIH-style
    data; synthetic opacity-pattern stand-in under zero egress)."""
    hist = _run(dict(
        dataset="chest_xray", model="densenet",
        learning_rate=0.003, client_optimizer="adam", epochs=2,
        batch_size=16,
    ), rounds=12)
    # 4 balanced classes -> chance 0.25
    assert hist[-1]["test_acc"] > 0.6, hist[-1]


@pytest.mark.slow
def test_medical_fets_segmentation_learns():
    """FeTS2021-style federated tumor segmentation (reference data/FeTS2021
    in SURVEY §2.2): 4-modality input, per-pixel 4-class labels."""
    hist = _run(dict(
        dataset="fets2021", model="unet",
        learning_rate=0.05, epochs=2,
    ), rounds=6)
    # background dominates (~90% pixels); segmentation must beat it
    assert hist[-1]["test_acc"] > 0.93, hist[-1]


def test_fedgraphnn_relation_prediction_learns():
    """Typed-edge relation prediction (reference app/fedgraphnn/
    subgraph_relation_pred: RGCN encoder + DistMult decoder)."""
    hist = _run(dict(
        dataset="subgraph_relation_pred", model="rgcn",
        learning_rate=0.003, client_optimizer="adam", epochs=6,
    ), rounds=16)
    # 5-way over all pairs; ~65% pairs are class 0 (no relation) so the
    # majority rate is ~0.65 — relation structure must push past it
    assert hist[-1]["test_acc"] > 0.75, hist[-1]


def test_fedgraphnn_recsys_rating_completion_learns():
    """Recsys user-item subgraph link prediction (reference
    app/fedgraphnn/recsys_subgraph_link_pred: MSE on rating logits)."""
    hist = _run(dict(
        dataset="recsys_subgraph_link_pred", model="gcn_recsys",
        learning_rate=0.01, client_optimizer="adam", epochs=6,
    ), rounds=20)
    # float labels => masked MSE; ratings span [1,5] (sd ~1.2 =>
    # mean-prediction MSE ~1.5) — completion must clearly beat the mean
    assert hist[-1]["test_loss"] < 0.8, hist[-1]


def test_all_reference_fedgraphnn_dirs_have_dataset_aliases():
    """Every task directory under the reference app/fedgraphnn tree must
    resolve through data.load (capability-parity check, VERDICT r3 #5)."""
    from fedml_tpu import data as data_mod

    for name in ("moleculenet", "moleculenet_reg", "ego_networks_node_clf",
                 "ego_networks_link_pred", "subgraph_link_pred",
                 "social_networks_graph_clf", "subgraph_relation_pred",
                 "recsys_subgraph_link_pred"):
        args = fedml_tpu.init(config=dict(
            dataset=name, model="gcn", debug_small_data=True,
            client_num_in_total=2, client_num_per_round=2, comm_round=1,
            partition_method="homo", batch_size=8, random_seed=0))
        fed, class_num = data_mod.load(args)
        assert class_num >= 1 and len(fed.train_data_local_dict) == 2, name


def test_regression_float_labels_survive_packing():
    """Float regression targets must not be truncated to ints anywhere in
    the packing path (ADVICE r1: native pack int32 cast)."""
    args = fedml_tpu.init(config=dict(
        dataset="moleculenet_reg", model="gcn_reg", debug_small_data=True,
        client_num_in_total=3, client_num_per_round=3, comm_round=1,
        partition_method="hetero", partition_alpha=0.5, random_seed=0,
        batch_size=8,
    ))
    from fedml_tpu import data as data_mod

    fed, _ = data_mod.load(args)
    ys = np.concatenate([p.y for p in fed.train_data_local_dict.values()])
    assert ys.dtype == np.float32
    assert not np.allclose(ys, np.round(ys)), "float targets were truncated"
