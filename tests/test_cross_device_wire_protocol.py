"""Cross-device protocol conformance: a stand-in "phone" that speaks ONLY
the public wire format — raw-socket MQTT 3.1.1 + the documented msgpack
message encoding — against the real cross-device server over the real-wire
broker (VERDICT r4 #8; reference test/android_protocol_test/test_protocol.py
keeps the same kind of Python stand-in for its Android client).

The stand-in deliberately imports NOTHING from fedml_tpu.comm or
fedml_tpu.cross_silo: its MQTT framing and its ndarray codec are
re-implemented here from the protocol contract (MQTT 3.1.4 packets;
Message = msgpack map with msg_type/sender/receiver params, ndarrays as
ExtType 42 = msgpack((dtype, shape)) header + raw bytes; topics
fedml_{run}_0_{cid} down / fedml_{run}_{cid} up; >8 KB model payloads
offloaded to the blob store, key under "model_params" + "model_params_url").
Any server-side drift from that contract fails this test.
"""

import os
import socket
import struct
import threading
import time

import msgpack
import numpy as np
import pytest

# --- independent ndarray codec (protocol contract, NOT an import) ---------

_EXT = 42


def _nd_default(obj):
    arr = np.ascontiguousarray(np.asarray(obj))
    header = msgpack.packb((arr.dtype.str, list(arr.shape)))
    return msgpack.ExtType(_EXT, header + arr.tobytes())


def _nd_ext_hook(code, data):
    if code != _EXT:
        return msgpack.ExtType(code, data)
    up = msgpack.Unpacker()
    up.feed(data)
    dtype_str, shape = up.unpack()
    return np.frombuffer(data, dtype=np.dtype(dtype_str),
                         offset=up.tell()).reshape(shape).copy()


def wire_pack(obj) -> bytes:
    return msgpack.packb(obj, default=_nd_default, strict_types=False)


def wire_unpack(data: bytes):
    return msgpack.unpackb(data, ext_hook=_nd_ext_hook, strict_map_key=False)


# --- independent minimal MQTT 3.1.1 client --------------------------------

def _varlen(n: int) -> bytes:
    out = bytearray()
    while True:
        d, n = n % 128, n // 128
        out.append(d | (0x80 if n else 0))
        if not n:
            return bytes(out)


def _mqtt_str(s: str) -> bytes:
    b = s.encode()
    return struct.pack(">H", len(b)) + b


class StandInPhone:
    """Raw-socket MQTT client: CONNECT, SUBSCRIBE(qos0), PUBLISH(qos0),
    and a blocking packet reader. QoS0 subscription means the broker
    delivers every message at qos0 (min rule) — no acking needed."""

    def __init__(self, host: str, port: int, client_id: str):
        self.sock = socket.create_connection((host, port), timeout=30)
        self.sock.settimeout(60)
        var = (_mqtt_str("MQTT") + b"\x04" + b"\x02"  # level 4, clean session
               + struct.pack(">H", 60) + _mqtt_str(client_id))
        self._send(0x10, var)
        ptype, body = self._read_packet()
        assert ptype == 0x20 and body[1] == 0, f"CONNACK refused: {body!r}"

    def _send(self, ptype_flags: int, var: bytes) -> None:
        self.sock.sendall(bytes([ptype_flags]) + _varlen(len(var)) + var)

    def _read_exact(self, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = self.sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("broker closed")
            buf += chunk
        return buf

    def _read_packet(self):
        h = self._read_exact(1)[0]
        mult, length = 1, 0
        while True:
            d = self._read_exact(1)[0]
            length += (d & 0x7F) * mult
            if not d & 0x80:
                break
            mult *= 128
        return h & 0xF0, self._read_exact(length) if length else b""

    def subscribe(self, topic: str, pid: int = 1) -> None:
        var = struct.pack(">H", pid) + _mqtt_str(topic) + b"\x00"  # req qos0
        self._send(0x82, var)  # SUBSCRIBE has reserved flags 0b0010
        ptype, _ = self._read_packet()
        assert ptype == 0x90, "expected SUBACK"

    def publish(self, topic: str, payload: bytes) -> None:
        self._send(0x30, _mqtt_str(topic) + payload)  # qos0

    def read_publish(self):
        """Block until the next inbound PUBLISH; returns (topic, payload)."""
        while True:
            ptype, body = self._read_packet()
            if ptype != 0x30:
                continue  # ignore acks/pings
            tlen = struct.unpack(">H", body[:2])[0]
            topic = body[2:2 + tlen].decode()
            return topic, body[2 + tlen:]

    def close(self) -> None:
        try:
            self._send(0xE0, b"")  # DISCONNECT
        finally:
            self.sock.close()


def _delta_like(tree, delta):
    """The uplink protocol ships DELTAS (local - global), not full params
    (cross_silo/aggregator.py:108: new global = params + weighted-mean of
    deltas). A constant-0.01 delta = "training moved every weight by 0.01"."""
    if isinstance(tree, dict):
        return {k: _delta_like(v, delta) for k, v in tree.items()}
    if isinstance(tree, np.ndarray) and np.issubdtype(tree.dtype, np.floating):
        return np.full_like(tree, np.float32(delta))
    return np.zeros_like(tree)


def _fetch_params(msg: dict, store_dir: str):
    """Inline params or store-offloaded key+URL (the >8 KB path)."""
    mp = msg["model_params"]
    if isinstance(mp, (bytes, str)) and "model_params_url" in msg:
        key = mp if isinstance(mp, str) else mp.decode()
        with open(os.path.join(store_dir, key.replace("/", "_")), "rb") as f:
            return wire_unpack(f.read()), True
    return mp, False


def test_cross_device_round_with_wire_standin(tmp_path):
    """A full multi-round FL session driven end-to-end by the stand-in:
    CHECK->IDLE->INIT->upload->SYNC->upload->FINISH, all over real TCP."""
    import jax

    import fedml_tpu
    from fedml_tpu import data as data_mod, models as models_mod
    from fedml_tpu.comm.mqtt_wire import MqttBroker, MqttWireBroker
    from fedml_tpu.comm.store import FileSystemBlobStore
    from fedml_tpu.cross_device import ServerMNN

    store_dir = str(tmp_path / "store")
    args = fedml_tpu.init(config=dict(
        dataset="mnist", model="lr", debug_small_data=True,
        client_num_in_total=1, client_num_per_round=1, comm_round=2,
        learning_rate=0.1, batch_size=8, frequency_of_the_test=1,
        random_seed=0, global_model_file_path=str(tmp_path / "global.blob"),
    ))
    fed_data, output_dim = data_mod.load(args)
    model = models_mod.create(args, output_dim)
    sample = models_mod.sample_input_for(args, fed_data)
    variables = models_mod.init_params(model, jax.random.PRNGKey(0), sample)

    def apply_fn(v, x, train=False, rngs=None):
        return model.apply(v, x, train=train)

    broker = MqttBroker()  # real TCP broker on a random port
    server = ServerMNN(
        args, fed_data, variables, apply_fn=apply_fn, backend="MQTT_S3",
        broker=MqttWireBroker("127.0.0.1", broker.port,
                              client_id="server-rank0"),
        store=FileSystemBlobStore(root=store_dir),
    )

    # the stand-in subscribes BEFORE the server kicks the handshake so the
    # CHECK_CLIENT_STATUS broadcast is not lost (no retained messages)
    phone = StandInPhone("127.0.0.1", broker.port, "android-standin-1")
    phone.subscribe("fedml_0_0_1")  # downlink: {prefix}{run}_0_{cid}

    history = []
    server_err = []

    def run_server():
        try:
            history.extend(server.run() or [])
        except Exception as e:  # pragma: no cover
            server_err.append(e)

    t = threading.Thread(target=run_server, daemon=True)
    t.start()

    uplink = "fedml_0_1"
    saw = {"check": 0, "init": 0, "sync": 0, "finish": 0, "offloaded": 0}
    deadline = time.time() + 120
    phone.sock.settimeout(5)  # poll: surface a dead server between reads
    try:
        while time.time() < deadline:
            assert not server_err, server_err
            try:
                topic, payload = phone.read_publish()
            except socket.timeout:
                continue
            assert topic == "fedml_0_0_1"
            msg = wire_unpack(payload)
            mtype = msg["msg_type"]
            if mtype == 6:  # S2C_CHECK_CLIENT_STATUS -> announce IDLE
                saw["check"] += 1
                phone.publish(uplink, wire_pack({
                    "msg_type": 5, "sender": 1, "receiver": 0,
                    "client_status": "IDLE", "client_os": "Android",
                }))
            elif mtype in (1, 2):  # INIT_CONFIG / SYNC_MODEL
                saw["init" if mtype == 1 else "sync"] += 1
                params, was_offloaded = _fetch_params(msg, store_dir)
                saw["offloaded"] += was_offloaded
                assert isinstance(params, dict) and "params" in params
                round_idx = int(msg.get("round_idx", 0))
                update = _delta_like(params, 0.01)  # "on-device training"
                phone.publish(uplink, wire_pack({
                    "msg_type": 3, "sender": 1, "receiver": 0,
                    "model_params": update, "num_samples": 10,
                    "round_idx": round_idx,
                }))
            elif mtype == 7:  # FINISH
                saw["finish"] += 1
                break
        assert not server_err, server_err
        assert saw["check"] == 1 and saw["init"] == 1
        assert saw["sync"] == args.comm_round - 1
        assert saw["finish"] == 1, f"no FINISH within deadline: {saw}"
        # the >8 KB offload path was actually exercised (mnist lr ~31 KB)
        assert saw["offloaded"] >= 1
        t.join(timeout=30)
        assert not t.is_alive(), "server did not stop after FINISH"
        # the server's round history is real: one record per round
        assert len(history) == args.comm_round, history
        # server persisted the aggregated global model file each round
        blob_path = str(tmp_path / "global.blob")
        assert os.path.exists(blob_path)
        final = wire_unpack(open(blob_path, "rb").read())
        # aggregate of one client's (init + 0.01K) params: every float leaf
        # moved by ~0.01 per round
        k0 = np.asarray(variables["params"]["linear"]["kernel"])
        k2 = np.asarray(final["params/linear/kernel"])
        np.testing.assert_allclose(
            k2, k0 + 0.01 * args.comm_round, rtol=0, atol=1e-5)
    finally:
        phone.close()
        broker.close()
