"""mTLS for the gRPC WAN plane: secure exchange works, plaintext is refused
(the reference's gRPC plane is insecure-only; VERDICT r1 flagged it)."""

import datetime
import threading
import time

import pytest

from fedml_tpu.comm import Message
from fedml_tpu.comm.grpc_backend import GRPCCommManager, GrpcTls


def _make_ca_and_cert(tmp_path, name: str):
    """Self-signed CA + a leaf cert for 'localhost' signed by it."""
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import rsa
    from cryptography.x509.oid import NameOID

    def _key():
        return rsa.generate_private_key(public_exponent=65537, key_size=2048)

    def _name(cn):
        return x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, cn)])

    now = datetime.datetime.now(datetime.timezone.utc)
    ca_key = _key()
    ca_cert = (
        x509.CertificateBuilder()
        .subject_name(_name("fedml-tpu-test-ca"))
        .issuer_name(_name("fedml-tpu-test-ca"))
        .public_key(ca_key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - datetime.timedelta(minutes=5))
        .not_valid_after(now + datetime.timedelta(days=1))
        .add_extension(x509.BasicConstraints(ca=True, path_length=None), critical=True)
        .sign(ca_key, hashes.SHA256())
    )
    leaf_key = _key()
    leaf = (
        x509.CertificateBuilder()
        .subject_name(_name("localhost"))
        .issuer_name(ca_cert.subject)
        .public_key(leaf_key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - datetime.timedelta(minutes=5))
        .not_valid_after(now + datetime.timedelta(days=1))
        .add_extension(
            x509.SubjectAlternativeName([x509.DNSName("localhost")]),
            critical=False,
        )
        .sign(ca_key, hashes.SHA256())
    )
    pem = serialization.Encoding.PEM
    ca_path = tmp_path / "ca.pem"
    cert_path = tmp_path / f"{name}.pem"
    key_path = tmp_path / f"{name}.key"
    ca_path.write_bytes(ca_cert.public_bytes(pem))
    cert_path.write_bytes(leaf.public_bytes(pem))
    key_path.write_bytes(leaf_key.private_bytes(
        pem, serialization.PrivateFormat.TraditionalOpenSSL,
        serialization.NoEncryption()))
    return str(ca_path), str(cert_path), str(key_path)


def test_grpc_mtls_roundtrip_and_plaintext_refused(tmp_path):
    ca, cert, key = _make_ca_and_cert(tmp_path, "node")
    tls = GrpcTls(ca, cert, key, override_authority="localhost")
    base_port = 50910
    ip_cfg = {0: "127.0.0.1", 1: "127.0.0.1"}
    server = GRPCCommManager(rank=0, size=2, ip_config=ip_cfg,
                             base_port=base_port, tls=tls)
    client = GRPCCommManager(rank=1, size=2, ip_config=ip_cfg,
                             base_port=base_port, tls=tls)

    got = []

    class Obs:
        def receive_message(self, t, m):
            got.append((t, m))

    server.add_observer(Obs())
    t = threading.Thread(target=server.handle_receive_message, daemon=True)
    t.start()

    msg = Message("hello", 1, 0)
    msg.add_params("payload", {"w": [1.0, 2.0]})
    client.send_message(msg)
    deadline = time.time() + 15
    while not got and time.time() < deadline:
        time.sleep(0.05)
    assert got and got[0][0] == "hello"

    # a plaintext sender must NOT get through to the TLS server: point the
    # insecure manager at the TLS port via the documented host:port table
    import grpc

    insecure = GRPCCommManager(
        rank=1, size=2,
        ip_config={0: f"127.0.0.1:{base_port}", 1: "127.0.0.1"},
        base_port=base_port + 10,  # own listener well away from the server
        send_timeout=5.0,
    )
    with pytest.raises(grpc.RpcError):
        insecure.send_message(Message("evil", 1, 0))

    client.stop_receive_message()
    server.stop_receive_message()
    insecure.stop_receive_message()
