"""Buffered-async aggregation (PR 14): kill the synchronous round barrier.

Acceptance drills for the FedBuff-style engine: the ``async_buffer_size ==
cohort`` fallback must replay the synchronous engine bit for bit (including
the SCAFFOLD control-variate arena and codec error-feedback residuals),
eval/checkpoint boundaries must flush the partial buffer, a mid-run restart
must resume from the model-version log with no duplicate or lost committed
updates, the seeded delay plan must replay exactly, and every commit
record's phase breakdown must still sum to its wall-clock.
"""

import math
import threading

import jax
import numpy as np
import pytest

import fedml_tpu
from fedml_tpu.comm import LoopbackHub
from fedml_tpu.comm.resilience import ClientDelayPlan
from fedml_tpu.cross_silo import FedML_Horizontal
from fedml_tpu.simulation import AsyncFedSimulator, FedSimulator, build_simulator


def _build(**kw):
    cfg = dict(
        dataset="digits", model="lr", partition_method="homo",
        client_num_in_total=8, client_num_per_round=8, comm_round=6,
        learning_rate=0.3, epochs=1, batch_size=32,
        frequency_of_the_test=3, random_seed=0,
    )
    cfg.update(kw)
    args = fedml_tpu.init(config=cfg)
    return build_simulator(args)


def _trees_equal(a, b) -> bool:
    flat_a, _ = jax.tree.flatten(a)
    flat_b, _ = jax.tree.flatten(b)
    return len(flat_a) == len(flat_b) and all(
        np.array_equal(np.asarray(x), np.asarray(y))
        for x, y in zip(flat_a, flat_b))


# --- off-by-default & lockstep fallback -------------------------------------


def test_async_off_by_default_builds_sync_engine():
    sim, _ = _build()
    assert type(sim) is FedSimulator
    sim2, _ = _build(async_mode=True)
    assert isinstance(sim2, AsyncFedSimulator)


@pytest.mark.parametrize("kw", [
    dict(federated_optimizer="SCAFFOLD", comm_round=4),
    dict(comm_codec="delta|topk:0.01|q8", comm_round=4),
], ids=["scaffold_arena", "codec_ef_residuals"])
def test_lockstep_fallback_bit_exact(kw):
    """K == cohort with zero skew is the synchronous engine bit for bit —
    including SCAFFOLD's client control-variate arena and the codec's
    per-client error-feedback residuals, the two pieces of cross-round
    state most likely to drift under a reordered fold (the engine rejects
    the two knobs together, so each variant exercises one)."""
    sync_sim, sync_apply = _build(**kw)
    sync_hist = sync_sim.run(sync_apply, log_fn=None)
    async_sim, async_apply = _build(async_mode=True, **kw)
    assert async_sim._lockstep
    async_hist = async_sim.run(async_apply, log_fn=None)

    assert _trees_equal(sync_sim.params, async_sim.params)
    assert _trees_equal(sync_sim.server_state, async_sim.server_state)
    assert [h.get("test_acc") for h in sync_hist] \
        == [h.get("test_acc") for h in async_hist]


def test_staleness_scale_none_is_identical_bits():
    """The robust sanitizer with staleness_scale=None must be byte-for-byte
    the synchronous code path (the z-scores see unscaled norms)."""
    from fedml_tpu.core.robust import sanitize_stacked

    rng = np.random.default_rng(7)
    stacked = {"w": np.asarray(rng.normal(size=(6, 5)), np.float32)}
    w = np.ones((6,), np.float32)
    base = sanitize_stacked(stacked, w, 6.0)
    none = sanitize_stacked(stacked, w, 6.0, staleness_scale=None)
    assert _trees_equal(base[0], none[0])
    for got, want in zip(none[1:], base[1:]):
        assert np.array_equal(np.asarray(got), np.asarray(want))


# --- buffered regime --------------------------------------------------------


def test_buffered_run_commits_phase_sums_and_goodput():
    """K=2 under 10× seeded skew: one history record per commit, each
    record's phases summing exactly to its wall-clock, committed updates
    conserved, staleness bounded, and positive virtual-time goodput."""
    sim, apply_fn = _build(
        async_mode=True, async_buffer_size=2, async_delay_skew=10.0)
    hist = sim.run(apply_fn, log_fn=None)
    stats = sim.async_stats()

    assert stats["version"] == len(hist)
    assert stats["committed_updates"] == 6 * 8  # every update lands
    assert stats["committed_updates"] == sum(h["buffer_fill"] for h in hist)
    assert stats["virtual_time_s"] > 0
    assert stats["goodput_updates_per_s"] > 0
    for h in hist:
        assert math.isclose(sum(h["phases"].values()), h["round_time"],
                            rel_tol=1e-6, abs_tol=1e-9)
    # phase-to-record assignment is by completion interval (deferred
    # readback), so the commit phase shows up across the run, not
    # necessarily on every record
    assert any("commit" in h["phases"] for h in hist)
    assert max(h["staleness_max"] for h in hist) >= 1  # skew makes staleness
    assert hist[-1]["test_acc"] > 0.7, hist[-1]


def test_eval_mid_buffer_forces_flush():
    """An eval boundary hitting a partially-filled buffer must flush it:
    cohort=8 with K=3 leaves 8 mod 3 = 2 updates buffered at every
    generation boundary, and eval-every-generation must still always see a
    committed model — so flush records carry test_acc at under-K fill."""
    sim, apply_fn = _build(
        async_mode=True, async_buffer_size=3, async_delay_skew=10.0,
        frequency_of_the_test=1)
    hist = sim.run(apply_fn, log_fn=None)

    flushed = [h for h in hist if h["buffer_fill"] < 3]
    assert flushed, "expected partial-buffer flush commits"
    assert any("test_acc" in h for h in flushed)
    assert sim.async_stats()["committed_updates"] == 6 * 8


def test_delay_plan_replays_exactly():
    plan_a = ClientDelayPlan(seed=3, base_s=1.0, skew=10.0, jitter=0.2)
    plan_b = ClientDelayPlan(seed=3, base_s=1.0, skew=10.0, jitter=0.2)
    plan_c = ClientDelayPlan(seed=4, base_s=1.0, skew=10.0, jitter=0.2)
    grid_a = [plan_a.delay_s(c, g) for c in range(8) for g in range(6)]
    grid_b = [plan_b.delay_s(c, g) for c in range(8) for g in range(6)]
    grid_c = [plan_c.delay_s(c, g) for c in range(8) for g in range(6)]
    assert grid_a == grid_b
    assert grid_a != grid_c
    # the 10× skew actually materializes as a heavy tail
    assert max(grid_a) / min(grid_a) > 5.0


def test_buffered_run_is_deterministic():
    """Same seed → identical commit schedule, virtual clock, and params."""
    sim_a, apply_a = _build(
        async_mode=True, async_buffer_size=2, async_delay_skew=10.0)
    hist_a = sim_a.run(apply_a, log_fn=None)
    sim_b, apply_b = _build(
        async_mode=True, async_buffer_size=2, async_delay_skew=10.0)
    hist_b = sim_b.run(apply_b, log_fn=None)

    assert _trees_equal(sim_a.params, sim_b.params)
    assert sim_a.async_stats() == sim_b.async_stats()
    assert [h["buffer_fill"] for h in hist_a] \
        == [h["buffer_fill"] for h in hist_b]
    assert [h["virtual_time_s"] for h in hist_a] \
        == [h["virtual_time_s"] for h in hist_b]


# --- restart without round boundaries ---------------------------------------


def test_checkpoint_resume_mid_buffer_no_lost_or_duplicate_updates(tmp_path):
    """Interrupt after 4 of 6 generations and resume from the model-version
    log: the resumed run must land bit-exact on an uninterrupted run with
    the same checkpoint cadence (checkpoint boundaries force buffer
    flushes, so the cadence is part of the commit partitioning)."""
    kw = dict(async_mode=True, async_buffer_size=3, async_delay_skew=10.0,
              checkpoint_frequency=2)

    full_sim, full_apply = _build(checkpoint_dir=str(tmp_path / "full"), **kw)
    full_sim.run(full_apply, log_fn=None)
    full_stats = full_sim.async_stats()

    part_sim, part_apply = _build(
        checkpoint_dir=str(tmp_path / "part"), comm_round=4, **kw)
    part_sim.run(part_apply, log_fn=None)
    interrupted = part_sim.async_stats()

    res_sim, res_apply = _build(
        checkpoint_dir=str(tmp_path / "part"), **kw)
    res_sim.run(res_apply, log_fn=None)
    resumed = res_sim.async_stats()

    assert interrupted["version"] < resumed["version"]  # it actually resumed
    assert resumed == full_stats  # version/committed/virtual-time conserved
    assert _trees_equal(res_sim.params, full_sim.params)


# --- cross-silo FSM ---------------------------------------------------------


def _silo_args(**kw):
    base = dict(
        dataset="mnist", model="lr", debug_small_data=True,
        client_num_in_total=3, client_num_per_round=3, comm_round=6,
        learning_rate=0.1, epochs=1, batch_size=8, frequency_of_the_test=1,
        random_seed=0,
    )
    base.update(kw)
    return fedml_tpu.init(config=base)


def test_cross_silo_async_loopback_full_run():
    """The live server FSM in async mode: 3 free-running silos, K=2 —
    comm_round counts commits, every upload is folded (none shed at this
    scale), and the model still learns."""
    args = _silo_args(async_mode=True, async_buffer_size=2)
    hub = LoopbackHub()
    server = FedML_Horizontal(args, 0, 3, backend="LOOPBACK", hub=hub)
    clients = [FedML_Horizontal(args, r, 3, backend="LOOPBACK", hub=hub)
               for r in range(1, 4)]
    threads = [threading.Thread(target=c.run, daemon=True) for c in clients]
    for t in threads:
        t.start()
    server.start()
    server.run()
    for t in threads:
        t.join(timeout=60)

    assert server.model_version == 6
    assert len(server.history) == 6
    assert server.committed_updates == 6 * 2
    assert server.shed_updates == 0
    assert all(h["n_updates"] == 2 for h in server.history)
    assert server.history[-1]["test_acc"] > 0.4, server.history[-1]


def test_cross_silo_async_rejects_watchdog():
    args = _silo_args(async_mode=True, watchdog_factor=3.0)
    with pytest.raises(ValueError, match="watchdog"):
        FedML_Horizontal(args, 0, 3, backend="LOOPBACK", hub=LoopbackHub())
