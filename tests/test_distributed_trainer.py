"""Cheetah distributed LM trainer: dp/tp/sp shardings + ring attention."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fedml_tpu.parallel.trainer import (
    DistTrainConfig,
    DistributedLMTrainer,
    transformer_param_specs,
)
from jax.sharding import PartitionSpec as P


def _toy_data(vocab, B, T, seed=0):
    rng = np.random.default_rng(seed)
    while True:
        # learnable pattern: next token = (token + 1) % vocab
        start = rng.integers(0, vocab, (B, 1))
        seq = (start + np.arange(T + 1)) % vocab
        yield seq[:, :-1].astype(np.int32), seq[:, 1:].astype(np.int32)


def test_param_specs_megatron_layout():
    cfg = DistTrainConfig(dp=8, tp=1, sp=1)
    tr = DistributedLMTrainer(cfg, vocab_size=64, dim=32, num_heads=4,
                              num_layers=1, max_len=64, dtype=jnp.float32)
    specs = transformer_param_specs(tr.params)
    flat = {
        "/".join(str(getattr(k, "key", k)) for k in path): s
        for path, s in jax.tree_util.tree_flatten_with_path(
            specs, is_leaf=lambda x: isinstance(x, P))[0]
    }
    qkv = [v for k, v in flat.items() if "qkv" in k and k.endswith("kernel")]
    proj = [v for k, v in flat.items() if "proj" in k and k.endswith("kernel")]
    assert qkv == [P(None, "model")]
    assert proj == [P("model", None)]


@pytest.mark.parametrize("dp,tp,sp", [(8, 1, 1), (2, 2, 2), (1, 1, 8)])
def test_distributed_lm_trains(dp, tp, sp):
    cfg = DistTrainConfig(dp=dp, tp=tp, sp=sp, lr=1e-2)
    vocab, B, T = 32, 8, 16
    tr = DistributedLMTrainer(cfg, vocab_size=vocab, dim=64, num_heads=4,
                              num_layers=2, max_len=T, dtype=jnp.float32)
    losses = tr.train(_toy_data(vocab, B, T), steps=30, log_fn=None)
    assert losses[-1] < losses[0] * 0.7, (losses[0], losses[-1])


def test_distributed_lm_chunked_ce_matches_full():
    """ce_chunk must be a pure memory lever: same loss trajectory as the
    full-logit CE under a tp-sharded head (vocab-sharded chunk logits +
    log-softmax collective compose under GSPMD)."""
    vocab, B, T = 32, 8, 16
    losses = {}
    for chunk in (0, 8):
        cfg = DistTrainConfig(dp=4, tp=2, sp=1, lr=1e-2, ce_chunk=chunk)
        tr = DistributedLMTrainer(cfg, vocab_size=vocab, dim=64, num_heads=4,
                                  num_layers=2, max_len=T, dtype=jnp.float32)
        losses[chunk] = tr.train(_toy_data(vocab, B, T), steps=10, log_fn=None)
    np.testing.assert_allclose(losses[0], losses[8], rtol=1e-4, atol=1e-5)


def test_ring_attention_matches_dense():
    """SP ring attention must equal dense attention numerically."""
    from jax.sharding import PartitionSpec as P
    from jax import shard_map

    from fedml_tpu.ops.attention import multihead_attention, ring_attention
    from fedml_tpu.parallel import AXIS_SEQ, MeshConfig, create_mesh

    mesh = create_mesh(MeshConfig(axes=((AXIS_SEQ, 8),)))
    B, T, H, D = 2, 64, 4, 16
    rng = np.random.default_rng(0)
    q, k, v = (jnp.asarray(rng.normal(size=(B, T, H, D)), jnp.float32) for _ in range(3))
    dense = multihead_attention(q, k, v, causal=True)
    spec = P(None, AXIS_SEQ, None, None)
    ring = shard_map(
        lambda q, k, v: ring_attention(q, k, v, AXIS_SEQ, causal=True),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec, check_vma=False,
    )(q, k, v)
    np.testing.assert_allclose(np.asarray(dense), np.asarray(ring), atol=2e-5)


def test_ulysses_attention_matches_dense():
    """All-to-all SP (Ulysses) must equal dense attention numerically —
    causal and bidirectional."""
    from jax.sharding import PartitionSpec as P
    from jax import shard_map

    from fedml_tpu.ops.attention import multihead_attention, ulysses_attention
    from fedml_tpu.parallel import AXIS_SEQ, MeshConfig, create_mesh

    mesh = create_mesh(MeshConfig(axes=((AXIS_SEQ, 4),)),
                       devices=jax.devices()[:4])
    B, T, H, D = 2, 64, 4, 16
    rng = np.random.default_rng(1)
    q, k, v = (jnp.asarray(rng.normal(size=(B, T, H, D)), jnp.float32)
               for _ in range(3))
    spec = P(None, AXIS_SEQ, None, None)
    for causal in (True, False):
        dense = multihead_attention(q, k, v, causal=causal, impl="dense")
        uly = shard_map(
            lambda q, k, v, c=causal: ulysses_attention(
                q, k, v, AXIS_SEQ, causal=c),
            mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
            check_vma=False,
        )(q, k, v)
        np.testing.assert_allclose(np.asarray(dense), np.asarray(uly),
                                   atol=2e-5)


def test_ulysses_rejects_indivisible_heads():
    from jax.sharding import PartitionSpec as P
    from jax import shard_map

    from fedml_tpu.ops.attention import ulysses_attention
    from fedml_tpu.parallel import AXIS_SEQ, MeshConfig, create_mesh

    mesh = create_mesh(MeshConfig(axes=((AXIS_SEQ, 8),)))
    q = jnp.zeros((1, 16, 4, 8), jnp.float32)  # 4 heads < 8 devices
    spec = P(None, AXIS_SEQ, None, None)
    with pytest.raises(ValueError, match="divisible"):
        shard_map(
            lambda q, k, v: ulysses_attention(q, k, v, AXIS_SEQ),
            mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
            check_vma=False,
        )(q, q, q)


def test_distributed_lm_ulysses_matches_ring_forward():
    """The same params through ring-SP and ulysses-SP must give the same
    logits (both compute exact attention, just different collectives)."""
    cfg_r = DistTrainConfig(dp=2, tp=1, sp=4, sp_impl="ring", lr=1e-2)
    cfg_u = DistTrainConfig(dp=2, tp=1, sp=4, sp_impl="ulysses", lr=1e-2)
    vocab, B, T = 32, 4, 16
    tr_r = DistributedLMTrainer(cfg_r, vocab_size=vocab, dim=64, num_heads=4,
                                num_layers=2, max_len=T, dtype=jnp.float32)
    tr_u = DistributedLMTrainer(cfg_u, vocab_size=vocab, dim=64, num_heads=4,
                                num_layers=2, max_len=T, dtype=jnp.float32)
    l_r = tr_r.train(_toy_data(vocab, B, T), steps=10, log_fn=None)
    l_u = tr_u.train(_toy_data(vocab, B, T), steps=10, log_fn=None)
    np.testing.assert_allclose(l_r, l_u, rtol=2e-4)
