"""Workload-aware cohort scheduling (VERDICT #10): the DP bucket scheduler
wired into FedSimulator cuts padded compute for skewed cohorts while
matching the even path's aggregation numerics."""

import time

import jax
import numpy as np
import pytest

import fedml_tpu
from fedml_tpu.core.scheduler import bucket_schedule, dp_schedule
from fedml_tpu.data import load as load_data
from fedml_tpu.parallel import AXIS_CLIENT, MeshConfig, create_mesh
from fedml_tpu.simulation import build_simulator


def test_bucket_schedule_partitions_and_cuts_padding():
    # 12 tiny clients (1 batch) + 4 huge (32 batches), axis 4
    counts = [1] * 12 + [32] * 4
    buckets = bucket_schedule(counts, axis=4, max_buckets=4)
    covered = np.sort(np.concatenate([p for p, _ in buckets]))
    np.testing.assert_array_equal(covered, np.arange(16))
    # padded cost: even = 16 slots * 32 wide = 512; optimal split
    # {12 small} + {4 big} costs 12*1 + 4*32 = 140
    cost = sum((-(-len(p) // 4)) * 4 * w for p, w in buckets)
    assert cost <= 12 * 1 + 4 * 32
    widths = [w for _, w in buckets]
    assert widths == sorted(widths)


def test_bucket_schedule_single_bucket_uniform():
    buckets = bucket_schedule([5, 5, 5, 5], axis=2, max_buckets=4)
    # widths quantize up to powers of two (compile-cache stability)
    assert len(buckets) == 1 and buckets[0][1] == 8


def test_bucket_schedule_respects_width_cap():
    # a 47-batch client must NOT have its width quantized past the caller's
    # per-client batch cap (that would silently raise its training budget
    # and aggregation weight vs the even path)
    buckets = bucket_schedule([1, 1, 47], axis=1, max_buckets=2, max_width=24)
    assert max(w for _, w in buckets) == 24


def test_dp_schedule_balances_makespan():
    assignment, costs = dp_schedule(
        [10, 9, 8, 1, 1, 1], np.ones(3), np.full(3, np.inf)
    )
    assert sorted(sum(assignment, [])) == list(range(6))
    assert costs.max() <= 11  # LPT bound; optimal makespan is 10


def _skewed_args(schedule: str, rounds: int = 2):
    return fedml_tpu.init(config=dict(
        dataset="synthetic_skewed", model="lr", debug_small_data=True,
        client_num_in_total=32, client_num_per_round=32, comm_round=rounds,
        learning_rate=0.1, epochs=1, batch_size=256,
        frequency_of_the_test=100, random_seed=0,
        cohort_schedule=schedule, backend="TPU",
    ))


@pytest.fixture(scope="module")
def skewed_fed_data():
    """16 clients, heavy-tailed sizes: 12 with ~1 batch, 4 with ~24 batches."""
    from fedml_tpu.data.federated import ArrayPair, build_federated_data

    rng = np.random.default_rng(0)
    # big enough that compute dominates dispatch overhead on the test mesh:
    # even mode pads 24 one-batch clients to the 24-batch width; the 8 heavy
    # clients align with the 8-device axis so the heavy bucket carries no
    # dead slots. INTERLEAVED on purpose: the bucketed schedule reorders
    # this cohort, so the numerics test below proves schedule-independent
    # shuffles/RNG (a sorted fixture would mask ordering bugs).
    sizes = [64, 64, 64, 6100] * 8
    total = sum(sizes)
    x = rng.normal(size=(total, 2048)).astype(np.float32)
    w = rng.normal(size=(2048,))
    y = (x @ w > 0).astype(np.int64)
    idx_map, start = {}, 0
    for c, n in enumerate(sizes):
        idx_map[c] = list(range(start, start + n))
        start += n
    tx = rng.normal(size=(64, 2048)).astype(np.float32)
    ty = (tx @ w > 0).astype(np.int64)
    return build_federated_data(
        ArrayPair(x, y), ArrayPair(tx, ty), idx_map, class_num=2
    )


def _run(schedule, fed_data, mesh):
    args = _skewed_args(schedule)
    sim, apply_fn = build_simulator(args, fed_data=fed_data, mesh=mesh)
    hist = sim.run(apply_fn, log_fn=None)
    return sim, hist


def test_bucketed_matches_even_numerics(skewed_fed_data):
    mesh = create_mesh(MeshConfig(axes=((AXIS_CLIENT, 4),)),
                       devices=jax.devices()[:4])
    sim_even, _ = _run("even", skewed_fed_data, mesh)
    sim_bkt, hist = _run("bucketed", skewed_fed_data, mesh)
    assert sim_bkt._bucketed
    leaves_e = jax.tree.leaves(sim_even.params)
    leaves_b = jax.tree.leaves(sim_bkt.params)
    for a, b in zip(leaves_e, leaves_b):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5,
            err_msg="bucketed aggregation diverged from even path",
        )
    assert np.isfinite(hist[-1]["train_loss"])


@pytest.mark.slow
def test_bucketed_beats_even_on_skewed_cohort(skewed_fed_data):
    """The done-criterion: on the 8-device mesh a skewed cohort's round time
    under the DP schedule beats the even (pad-to-max) placement."""
    mesh = create_mesh(MeshConfig(axes=((AXIS_CLIENT, 8),)),
                       devices=jax.devices()[:8])

    def timed(schedule, rounds=6):
        args = _skewed_args(schedule, rounds=rounds)
        sim, apply_fn = build_simulator(args, fed_data=skewed_fed_data, mesh=mesh)
        # wall-to-wall including compile (run() drains the async dispatch
        # queue before returning, so this wall-clock is honest — per-round
        # timers are not, see FedSimulator.run). The bucketed side compiles
        # MORE programs (one per width class + finalize), so the win below
        # is in spite of its compile handicap.
        t0 = time.perf_counter()
        sim.run(apply_fn, log_fn=None)
        return (time.perf_counter() - t0) / rounds

    t_even = timed("even")
    t_bucketed = timed("bucketed")
    # 24/32 clients are ~24x overpadded in even mode; demand a real win
    assert t_bucketed < 0.75 * t_even, (t_bucketed, t_even)
