"""Real on-disk dataset format parsers against tiny committed fixtures.

Each test exercises the parse-if-present path through ``data.load`` (or the
parser directly), proving a ``data_cache_dir`` laid out like the reference's
downloads is consumed — synthetic fallbacks engage only when files are
absent.
"""

import os

import numpy as np

import fedml_tpu
from fedml_tpu import data as data_mod
from fedml_tpu.data import real_formats

FIX = os.path.join(os.path.dirname(__file__), "fixtures", "real_formats")


def _args(dataset, cache, **kw):
    base = dict(dataset=dataset, data_cache_dir=cache,
                client_num_in_total=2, partition_method="homo", random_seed=0)
    base.update(kw)
    return fedml_tpu.init(config=base)


def test_cinic10_image_folder():
    fed, class_num = data_mod.load(_args("cinic10", os.path.join(FIX, "cinic10")))
    assert class_num == 2
    assert fed.train_data_global.x.shape == (8, 32, 32, 3)
    assert fed.test_data_global.x.shape == (4, 32, 32, 3)
    # pixel scaling + class separation (class dirs had different means)
    x, y = fed.train_data_global.x, fed.train_data_global.y
    assert 0.0 <= x.min() and x.max() <= 1.0
    assert x[y == 1].mean() > x[y == 0].mean() + 0.2


def test_landmarks_natural_user_partition():
    fed, class_num = data_mod.load(_args("gld23k", os.path.join(FIX, "gld23k")))
    assert class_num == 2  # classes {3, 10} remapped to {0, 1}
    assert fed.client_num == 3  # three mapping users = three clients
    sizes = sorted(len(v) for v in fed.train_data_local_dict.values())
    assert sizes == [3, 3, 3]
    assert fed.train_data_global.x.shape[1:] == (64, 64, 3)
    assert len(fed.test_data_global.x) == 3
    assert set(np.unique(fed.train_data_global.y)) <= {0, 1}


def test_uci_susy_csv():
    fed, class_num = data_mod.load(_args("UCI", os.path.join(FIX, "uci")))
    assert class_num == 2
    n = len(fed.train_data_global.x) + len(fed.test_data_global.x)
    assert n == 24
    assert fed.train_data_global.x.shape[1] == 8
    assert set(np.unique(fed.train_data_global.y)) <= {0, 1}


def test_lending_club_csv():
    fed, class_num = data_mod.load(
        _args("lending_club_loan", os.path.join(FIX, "lending")))
    assert class_num == 2
    xs = np.concatenate([fed.train_data_global.x, fed.test_data_global.x])
    ys = np.concatenate([fed.train_data_global.y, fed.test_data_global.y])
    # loan_amnt + int_rate are numeric; 'id' is an identifier (excluded —
    # it leaks split position on the real file)
    assert xs.shape == (20, 2)
    # every third row was Charged Off -> bad (1)
    assert ys.sum() == 7
    # standardized features
    np.testing.assert_allclose(xs.mean(0), 0.0, atol=1e-4)


def test_lending_club_sparse_numeric_column(tmp_path):
    """Rows with missing values in a numeric column must be imputed, not
    dropped (the real loan.csv has ~50%-sparse numeric columns)."""
    import csv as _csv

    p = tmp_path / "loan.csv"
    with open(p, "w", newline="") as f:
        w = _csv.DictWriter(f, fieldnames=["loan_amnt", "dti", "loan_status"])
        w.writeheader()
        for i in range(10):
            w.writerow({"loan_amnt": 100 + i,
                        "dti": "" if i % 2 else str(10.0 + i),
                        "loan_status": "Fully Paid"})
    pair = real_formats.load_lending_club_csv(str(p))
    assert pair.x.shape == (10, 2)  # no row dropped
    assert np.isfinite(pair.x).all()


def test_nus_wide_txt():
    fed, class_num = data_mod.load(
        _args("NUS_WIDE", os.path.join(FIX, "nus_wide")))
    assert class_num == 2
    assert fed.train_data_global.x.shape == (12, 7)  # 4 + 3 feature cols
    assert len(fed.test_data_global.x) == 6
    # labels alternate by construction
    np.testing.assert_array_equal(
        fed.train_data_global.y[:4], [0, 1, 0, 1])


def test_nus_wide_parser_direct():
    feats, labels, concepts = real_formats.load_nus_wide(
        os.path.join(FIX, "nus_wide"), "Test")
    assert feats.shape == (6, 7)
    assert labels.shape == (6, 2)
    assert concepts == ["sky", "water"]


def test_synthetic_fallback_when_absent(tmp_path):
    fed, class_num = data_mod.load(
        _args("cinic10", str(tmp_path), debug_small_data=True))
    assert class_num == 10  # synthetic cifar-family stand-in
