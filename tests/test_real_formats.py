"""Real on-disk dataset format parsers against tiny committed fixtures.

Each test exercises the parse-if-present path through ``data.load`` (or the
parser directly), proving a ``data_cache_dir`` laid out like the reference's
downloads is consumed — synthetic fallbacks engage only when files are
absent.
"""

import os

import numpy as np
import pytest

import fedml_tpu
from fedml_tpu import data as data_mod
from fedml_tpu.data import real_formats

FIX = os.path.join(os.path.dirname(__file__), "fixtures", "real_formats")


def _args(dataset, cache, **kw):
    base = dict(dataset=dataset, data_cache_dir=cache,
                client_num_in_total=2, partition_method="homo", random_seed=0)
    base.update(kw)
    return fedml_tpu.init(config=base)


def test_cinic10_image_folder():
    fed, class_num = data_mod.load(_args("cinic10", os.path.join(FIX, "cinic10")))
    assert class_num == 2
    assert fed.train_data_global.x.shape == (8, 32, 32, 3)
    assert fed.test_data_global.x.shape == (4, 32, 32, 3)
    # pixel scaling + class separation (class dirs had different means)
    x, y = fed.train_data_global.x, fed.train_data_global.y
    assert 0.0 <= x.min() and x.max() <= 1.0
    assert x[y == 1].mean() > x[y == 0].mean() + 0.2


def test_landmarks_natural_user_partition():
    fed, class_num = data_mod.load(_args("gld23k", os.path.join(FIX, "gld23k")))
    assert class_num == 2  # classes {3, 10} remapped to {0, 1}
    assert fed.client_num == 3  # three mapping users = three clients
    sizes = sorted(len(v) for v in fed.train_data_local_dict.values())
    assert sizes == [3, 3, 3]
    assert fed.train_data_global.x.shape[1:] == (64, 64, 3)
    assert len(fed.test_data_global.x) == 3
    assert set(np.unique(fed.train_data_global.y)) <= {0, 1}


def test_uci_susy_csv():
    fed, class_num = data_mod.load(_args("UCI", os.path.join(FIX, "uci")))
    assert class_num == 2
    n = len(fed.train_data_global.x) + len(fed.test_data_global.x)
    assert n == 24
    assert fed.train_data_global.x.shape[1] == 8
    assert set(np.unique(fed.train_data_global.y)) <= {0, 1}


def test_lending_club_csv():
    fed, class_num = data_mod.load(
        _args("lending_club_loan", os.path.join(FIX, "lending")))
    assert class_num == 2
    xs = np.concatenate([fed.train_data_global.x, fed.test_data_global.x])
    ys = np.concatenate([fed.train_data_global.y, fed.test_data_global.y])
    # loan_amnt + int_rate are numeric; 'id' is an identifier (excluded —
    # it leaks split position on the real file)
    assert xs.shape == (20, 2)
    # every third row was Charged Off -> bad (1)
    assert ys.sum() == 7
    # standardized features
    np.testing.assert_allclose(xs.mean(0), 0.0, atol=1e-4)


def test_lending_club_sparse_numeric_column(tmp_path):
    """Rows with missing values in a numeric column must be imputed, not
    dropped (the real loan.csv has ~50%-sparse numeric columns)."""
    import csv as _csv

    p = tmp_path / "loan.csv"
    with open(p, "w", newline="") as f:
        w = _csv.DictWriter(f, fieldnames=["loan_amnt", "dti", "loan_status"])
        w.writeheader()
        for i in range(10):
            w.writerow({"loan_amnt": 100 + i,
                        "dti": "" if i % 2 else str(10.0 + i),
                        "loan_status": "Fully Paid"})
    pair = real_formats.load_lending_club_csv(str(p))
    assert pair.x.shape == (10, 2)  # no row dropped
    assert np.isfinite(pair.x).all()


def test_nus_wide_txt():
    fed, class_num = data_mod.load(
        _args("NUS_WIDE", os.path.join(FIX, "nus_wide")))
    assert class_num == 2
    assert fed.train_data_global.x.shape == (12, 7)  # 4 + 3 feature cols
    assert len(fed.test_data_global.x) == 6
    # labels alternate by construction
    np.testing.assert_array_equal(
        fed.train_data_global.y[:4], [0, 1, 0, 1])


def test_nus_wide_parser_direct():
    feats, labels, concepts = real_formats.load_nus_wide(
        os.path.join(FIX, "nus_wide"), "Test")
    assert feats.shape == (6, 7)
    assert labels.shape == (6, 2)
    assert concepts == ["sky", "water"]


def test_synthetic_fallback_when_absent(tmp_path):
    fed, class_num = data_mod.load(
        _args("cinic10", str(tmp_path), debug_small_data=True))
    assert class_num == 10  # synthetic cifar-family stand-in


def test_chexpert_layout():
    """CheXpert-v1.0-small tree (reference chexpert/dataset.py:52-100):
    CSV path column with two stripped components, 14 multi-hot labels,
    blank/-1 handled by the zeros policy."""
    fed, class_num = data_mod.load(
        _args("chexpert", os.path.join(FIX, "chexpert")))
    assert class_num == 14
    x, y = fed.train_data_global.x, fed.train_data_global.y
    assert x.shape == (12, 64, 64, 3) and 0.0 <= x.min() and x.max() <= 1.0
    assert y.shape == (12, 14) and y.dtype == np.float32
    assert set(np.unique(y)) <= {0.0, 1.0}
    assert len(fed.test_data_global.x) == 4
    # blank (row i%4==1, col 5) and -1 (row i%4==2, col 7) map to 0 under
    # the zeros policy — the CSVs set those cells to positive otherwise
    assert y[1, 5] == 0.0 and y[2, 7] == 0.0
    # multi-hot float labels route to the bce loss family
    from fedml_tpu.algorithms.local_sgd import infer_loss_kind
    assert infer_loss_kind(object(), fed) == "bce"


@pytest.mark.slow
def test_chexpert_e2e_learns():
    """Real-format CheXpert fixtures through the full engine with the bce
    loss: loss must drop (labels are image-correlated by construction).
    Slow tier: 64x64 conv compiles dominate (~1 min on one CPU core)."""
    args = _args("chexpert", os.path.join(FIX, "chexpert"),
                 model="cnn_fedavg", comm_round=6, learning_rate=0.05,
                 epochs=2, batch_size=4, client_num_in_total=2,
                 client_num_per_round=2, frequency_of_the_test=5)
    history = fedml_tpu.run_simulation(args=args)
    losses = [h["train_loss"] for h in history]
    assert losses[-1] < losses[0] * 0.9, losses


def test_fets2021_nifti_and_npz():
    """FeTS2021 tree: partitioning CSV -> natural institution partition;
    subjects parsed from BOTH .npz bundles and .nii.gz volumes (the
    minimal NIfTI-1 reader against independently-written files)."""
    fed, class_num = data_mod.load(
        _args("fets2021", os.path.join(FIX, "fets2021")))
    assert class_num == 4
    x, y = fed.train_data_global.x, fed.train_data_global.y
    assert x.shape[1:] == (24, 24, 4)      # 4 modalities, 8-divisible H/W
    assert y.shape[1] == 24 * 24           # per-pixel labels flattened
    assert set(np.unique(y)) <= {0, 1, 2, 3}  # BraTS label 4 remapped to 3
    # natural partition: 2 institutions from the CSV
    assert fed.client_num == 2
    # slices are z-normalized per slice
    assert abs(float(x[0].mean())) < 0.2
    # test split exists (held-out subject slices)
    assert len(fed.test_data_global.x) > 0


def test_nifti_reader_roundtrip(tmp_path):
    """read_nifti against the fixture writer: exact voxel round-trip,
    Fortran order preserved, gz and plain, int16 and float32."""
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "scripts"))
    from make_medical_fixtures import write_nifti

    rng = np.random.default_rng(3)
    for dtype, suffix in ((np.float32, ".nii"), (np.int16, ".nii.gz")):
        vol = (rng.normal(0, 10, (5, 7, 3))).astype(dtype)
        p = str(tmp_path / f"v{suffix}")
        write_nifti(p, vol)
        out = real_formats.read_nifti(p)
        np.testing.assert_array_equal(out, vol)


def test_medical_synthetic_fallback(tmp_path):
    fed, class_num = data_mod.load(
        _args("chexpert", str(tmp_path), debug_small_data=True))
    assert class_num == 4  # synthetic 4-class stand-in
    fed, class_num = data_mod.load(
        _args("fets2021", str(tmp_path), debug_small_data=True))
    assert class_num == 4
