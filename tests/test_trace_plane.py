"""Trace plane: span shipping & assembly, Perfetto export, flight recorder,
and phase-anomaly detection (PR 10 acceptance)."""

import glob
import json
import os
import threading

import numpy as np
import pytest

import fedml_tpu
from fedml_tpu.comm import LoopbackHub, Message
from fedml_tpu.comm.loopback import LoopbackCommManager
from fedml_tpu.core import telemetry, trace_plane


@pytest.fixture(autouse=True)
def _fresh_telemetry():
    telemetry.configure(enabled=True, reset=True)
    yield
    telemetry.configure(enabled=True, reset=True)


# --- packing -----------------------------------------------------------------


def _mkspan(i, rank=1, round_idx=3):
    return {"kind": "span", "name": f"s{i}", "trace_id": "t1",
            "span_id": f"sp{i}", "parent_span_id": None,
            "round_idx": round_idx, "start": 100.0 + i, "duration": 0.5,
            "status": "ok", "rank": rank}


def test_pack_spans_caps_and_drop_order():
    spans = [_mkspan(i) for i in range(10)]
    payload, shipped, dropped = trace_plane.pack_spans(spans, 4, 1 << 20)
    assert (shipped, dropped) == (4, 6)
    got = trace_plane.unpack_spans(payload, origin_rank=1)
    # oldest dropped first: the newest spans are the round being shipped
    assert [r["name"] for r in got] == ["s6", "s7", "s8", "s9"]

    payload, shipped, dropped = trace_plane.pack_spans(spans, 256, 200)
    assert payload is not None and len(payload) <= 200
    assert shipped + dropped == 10

    payload, shipped, dropped = trace_plane.pack_spans(spans, 256, 1)
    assert payload is None and shipped == 0 and dropped == 10


def test_unpack_stamps_origin_rank():
    payload, _, _ = trace_plane.pack_spans(
        [dict(_mkspan(0), rank=99)], 16, 1 << 20)
    got = trace_plane.unpack_spans(payload, origin_rank=4)
    # the wire sender is authoritative — a span can't lie about its origin
    assert got[0]["rank"] == 4 and got[0]["shipped"] is True


# --- disabled-path wire parity ----------------------------------------------


def test_disabled_plane_leaves_message_byte_identical():
    msg = Message(1, 1, 0)
    msg.add_params("w", np.arange(4, dtype=np.float32))
    before = msg.to_bytes()
    assert not trace_plane.active()
    trace_plane.attach_spans(msg, 0, 1)
    trace_plane.attach_clock(msg)
    assert msg.to_bytes() == before

    trace_plane.configure(ship_spans=True)
    with telemetry.get_tracer().span("client.train", round_idx=0, rank=1):
        pass
    trace_plane.attach_spans(msg, 0, 1)
    trace_plane.attach_clock(msg)
    assert trace_plane.SPANS_KEY in msg.msg_params
    assert trace_plane.CLOCK_KEY in msg.msg_params
    assert msg.to_bytes() != before


def test_configure_unknown_key_raises():
    with pytest.raises(TypeError):
        trace_plane.configure(flght_recorder=True)


# --- span shipping parity across all four backends ---------------------------


def _client_round_spans(round_idx=3, rank=1):
    """One client round: train span with a nested step span, rank-attributed."""
    ctx = telemetry.new_round_context(round_idx)
    with telemetry.use_context(ctx):
        with telemetry.get_tracer().span("client.train", rank=rank):
            with telemetry.get_tracer().span("client.step", rank=rank):
                pass
    return ctx


def _ship_roundtrip(make_pair):
    """Ship one client round's spans through a backend pair; return the
    assembler signature of the ingested round tree."""
    trace_plane.configure(ship_spans=True)
    ctx = _client_round_spans()
    sender, receiver = make_pair()
    seen = []

    class Obs:
        def receive_message(self, t, msg):
            seen.append(msg)
            receiver.stop_receive_message()

    receiver.add_observer(Obs())
    rx = threading.Thread(target=receiver.handle_receive_message, daemon=True)
    rx.start()
    msg = Message(1, 1, 0)
    msg.add_params("w", np.arange(4, dtype=np.float32))
    shipped = trace_plane.attach_spans(msg, 3, 1)
    assert shipped == 2
    with telemetry.use_context(ctx):
        sender.send_message(msg)
    rx.join(timeout=10)
    assert not rx.is_alive(), "receiver never saw the message"
    payload = seen[0].get(trace_plane.SPANS_KEY)
    assert payload is not None
    fresh = trace_plane.ingest_shipped(payload, seen[0].get_sender_id())
    assert fresh == 2
    asm = trace_plane.get_assembler()
    assert asm.trace_ids() == {3: [ctx.trace_id]}
    return asm.signature(ctx.trace_id)


EXPECTED_SIG = (("client.train", 1, (("client.step", 1, ()),)),)


def test_span_shipping_parity_loopback():
    hub = LoopbackHub()
    sig = _ship_roundtrip(lambda: (LoopbackCommManager(1, 2, hub=hub),
                                   LoopbackCommManager(0, 2, hub=hub)))
    assert sig == EXPECTED_SIG


def test_span_shipping_parity_grpc():
    pytest.importorskip("grpc")
    from fedml_tpu.comm.grpc_backend import GRPCCommManager

    managers = []

    def make_pair():
        managers.append(GRPCCommManager(rank=1, size=2, base_port=19650))
        managers.append(GRPCCommManager(rank=0, size=2, base_port=19650))
        return managers[0], managers[1]

    try:
        assert _ship_roundtrip(make_pair) == EXPECTED_SIG
    finally:
        for m in managers:
            m._server.stop(grace=0)


def test_span_shipping_parity_mqtt_s3():
    from fedml_tpu.comm.mqtt_s3 import MqttS3CommManager
    from fedml_tpu.comm.pubsub import InProcessBroker
    from fedml_tpu.comm.store import InMemoryBlobStore

    broker, store = InProcessBroker(), InMemoryBlobStore()
    sig = _ship_roundtrip(
        lambda: (MqttS3CommManager(broker, store, rank=1, size=2),
                 MqttS3CommManager(broker, store, rank=0, size=2)))
    assert sig == EXPECTED_SIG


def test_span_shipping_parity_trpc():
    from fedml_tpu.comm.trpc_backend import TRPCCommManager

    managers = []

    def make_pair():
        managers.append(TRPCCommManager(rank=1, size=2, base_port=19670))
        managers.append(TRPCCommManager(rank=0, size=2, base_port=19670))
        return managers[0], managers[1]

    try:
        assert _ship_roundtrip(make_pair) == EXPECTED_SIG
    finally:
        for m in managers:
            try:
                m.stop_receive_message()
            except Exception:
                pass


def test_assembler_dedupes_by_span_id():
    asm = trace_plane.TraceAssembler()
    assert asm.add(_mkspan(0)) is True
    assert asm.add(_mkspan(0)) is False
    assert len(asm.spans()) == 1


# --- clock skew --------------------------------------------------------------


def test_clock_offset_recorded_from_handshake():
    trace_plane.configure(ship_spans=True)
    msg = Message(1, 2, 0)
    trace_plane.attach_clock(msg)
    wall = msg.get(trace_plane.CLOCK_KEY)
    assert wall is not None
    trace_plane.note_client_clock(2, wall - 5.0)  # client clock 5 s behind
    offsets = trace_plane.clock_offsets()
    assert offsets[(None, 2)] == pytest.approx(5.0, abs=0.5)


def test_export_applies_skew_correction():
    records = [
        {"kind": "clock_offset", "rank": 1, "offset": 5.0},
        dict(_mkspan(0, rank=1), start=100.0),
        dict(_mkspan(1, rank=0), start=105.0),
    ]
    doc = trace_plane.export_chrome_trace(records)
    by_name = {e["name"]: e for e in doc["traceEvents"] if e.get("ph") == "X"}
    # rank 1's clock runs 5 s behind: its span lands at the same corrected
    # instant as rank 0's, on separate tracks
    assert by_name["s0"]["ts"] == pytest.approx(105.0 * 1e6)
    assert by_name["s1"]["ts"] == pytest.approx(105.0 * 1e6)
    assert by_name["s0"]["tid"] == 1 and by_name["s1"]["tid"] == 0


# --- Chrome export -----------------------------------------------------------


def test_export_two_tenants_phase_sums_preserved():
    records = []
    for tenant, rank in (("a", 0), ("a", 1), ("b", 0)):
        records.append({
            "kind": "phase_record", "tenant": tenant, "rank": rank,
            "round": 2, "end": 200.0, "round_time": 1.5,
            "phases": [["dispatch", 0.5], ["device", 0.75], ["eval", 0.25]],
        })
    records.append({"kind": "instant", "name": "quarantine", "tenant": "a",
                    "rank": 0, "ts": 199.5, "round": 2, "clients": [3]})
    doc = trace_plane.export_chrome_trace(records)
    events = doc["traceEvents"]
    procs = {e["args"]["name"] for e in events
             if e.get("ph") == "M" and e["name"] == "process_name"}
    assert procs == {"tenant:a", "tenant:b"}
    slices = [e for e in events if e.get("ph") == "X"]
    by_track = {}
    for e in slices:
        by_track.setdefault((e["pid"], e["tid"]), []).append(e)
    assert len(by_track) == 3
    for evs in by_track.values():
        # phase slices are laid back-to-back and sum exactly to round_time
        assert sum(e["dur"] for e in evs) == pytest.approx(1.5 * 1e6)
        evs = sorted(evs, key=lambda e: e["ts"])
        for a, b in zip(evs, evs[1:]):
            assert a["ts"] + a["dur"] == pytest.approx(b["ts"])
    instants = [e for e in events if e.get("ph") == "i"]
    assert [e["name"] for e in instants] == ["quarantine"]
    assert instants[0]["args"]["clients"] == [3]
    # tenant filter keeps only that tenant's tracks
    only_b = trace_plane.export_chrome_trace(records, tenant="b")
    assert all(e["pid"] == 0 for e in only_b["traceEvents"])
    assert sum(1 for e in only_b["traceEvents"] if e.get("ph") == "X") == 3


# --- anomaly detection -------------------------------------------------------


def test_anomaly_detector_fires_and_stays_quiet():
    det = trace_plane.PhaseAnomalyDetector(
        window=16, z_thresh=8.0, warmup=3, min_seconds=0.05)
    for i in range(8):
        assert det.observe({"dispatch": 0.1 + 0.001 * (i % 3)}) == {}
    hit = det.observe({"dispatch": 5.0})
    assert "dispatch" in hit and hit["dispatch"] >= 8.0
    # the anomalous value must not become the new normal
    assert "dispatch" in det.observe({"dispatch": 5.0})
    assert det.observe({"dispatch": 0.1}) == {}


def test_anomaly_detector_min_seconds_floor():
    det = trace_plane.PhaseAnomalyDetector(
        window=16, z_thresh=8.0, warmup=3, min_seconds=0.05)
    for _ in range(8):
        det.observe({"codec": 0.0001})
    # 100x regression, but still under the absolute wall-clock floor
    assert det.observe({"codec": 0.01}) == {}


def test_on_round_record_annotates_history_and_counts():
    trace_plane.configure(anomaly_detection=True, anomaly_warmup=2,
                          anomaly_window=16, anomaly_min_seconds=0.01)
    for i in range(6):
        rec = {"round": i, "round_time": 0.2,
               "phases": {"dispatch": 0.1, "device": 0.1}}
        trace_plane.on_round_record(rec)
        assert "phase_anomalies" not in rec
    slow = {"round": 6, "round_time": 5.1,
            "phases": {"dispatch": 5.0, "device": 0.1}}
    trace_plane.on_round_record(slow)
    assert set(slow["phase_anomalies"]) == {"dispatch"}
    counters = telemetry.get_registry().snapshot()["counters"]
    assert counters.get('fedml_phase_anomalies_total{phase=dispatch}') == 1


def test_recompile_detector_flags_post_warmup_compiles():
    trace_plane.configure(anomaly_detection=True, anomaly_warmup=2,
                          anomaly_window=16)
    reg = telemetry.get_registry()
    for i in range(4):
        if i < 2:  # warmup compiles are expected and not flagged
            reg.counter("fedml_jax_compilation_events_total",
                        event="jit").inc()
        rec = {"round": i, "round_time": 0.1, "phases": {"dispatch": 0.1}}
        trace_plane.on_round_record(rec)
        assert "recompile_events" not in rec
    reg.counter("fedml_jax_compilation_events_total", event="jit").inc(2)
    rec = {"round": 4, "round_time": 0.1, "phases": {"dispatch": 0.1}}
    trace_plane.on_round_record(rec)
    assert rec["recompile_events"] == 2
    counters = reg.snapshot()["counters"]
    assert counters.get("fedml_recompiles_post_warmup_total") == 2


def test_absorb_planned_compiles_quiets_detector():
    # the scan engine compiles a NEW program for each block length — e.g. a
    # plan's short tail block lands after warmup by design; absorbing it
    # must keep the recompile counter at zero while a genuinely unplanned
    # compile right after still fires
    trace_plane.configure(anomaly_detection=True, anomaly_warmup=2,
                          anomaly_window=16)
    reg = telemetry.get_registry()
    for i in range(4):
        rec = {"round": i, "round_time": 0.1, "phases": {"dispatch": 0.1}}
        trace_plane.on_round_record(rec)
    reg.counter("fedml_jax_compilation_events_total", event="jit").inc(3)
    trace_plane.absorb_planned_compiles()
    rec = {"round": 4, "round_time": 0.1, "phases": {"dispatch": 0.1}}
    trace_plane.on_round_record(rec)
    assert "recompile_events" not in rec
    assert reg.counter_total("fedml_recompiles_post_warmup_total") == 0
    reg.counter("fedml_jax_compilation_events_total", event="jit").inc()
    rec = {"round": 5, "round_time": 0.1, "phases": {"dispatch": 0.1}}
    trace_plane.on_round_record(rec)
    assert rec["recompile_events"] == 1


def test_simulator_run_annotates_anomalies_when_quiet():
    """A clean small run must complete with the detector armed and produce
    zero anomaly annotations (the detector must not cry wolf)."""
    args = fedml_tpu.init(config=dict(
        dataset="mnist", model="lr", debug_small_data=True,
        client_num_in_total=8, client_num_per_round=4, comm_round=6,
        learning_rate=0.1, epochs=1, batch_size=8, frequency_of_the_test=5,
        random_seed=0, trace_anomaly_detection=True, trace_anomaly_warmup=2,
        # generous z + high floor: compile-round noise must stay quiet
        trace_anomaly_z=50.0, trace_anomaly_min_seconds=10.0,
    ))
    assert trace_plane.config().anomaly_detection is True
    history = fedml_tpu.run_simulation(args=args)
    assert len(history) == 6
    assert all("phase_anomalies" not in h for h in history)


# --- flight recorder ---------------------------------------------------------


def test_flight_dump_bundle_roundtrip(tmp_path):
    trace_plane.configure(flight_recorder=True, flight_dir=str(tmp_path),
                          ship_spans=True)
    with telemetry.get_tracer().span("server.round", round_idx=1, rank=0):
        pass
    trace_plane.record_instant("rollback", round_idx=1,
                               attrs={"excluded": [2]})
    trace_plane.on_round_record(
        {"round": 1, "round_time": 0.3, "phases": {"dispatch": 0.3}})
    path = trace_plane.flight_dump("watchdog_rollback")
    assert path is not None and os.path.exists(path)
    with open(path) as f:
        bundle = json.load(f)
    assert bundle["kind"] == "flight_bundle"
    assert bundle["reason"] == "watchdog_rollback"
    kinds = {r.get("kind") for r in bundle["records"]}
    assert {"span", "instant", "phase_record"} <= kinds
    assert "registry" in bundle
    # the bundle replays through the exporter without the live process
    doc = trace_plane.export_chrome_trace(trace_plane.load_records(path))
    assert any(e.get("ph") == "X" for e in doc["traceEvents"])
    assert any(e.get("ph") == "i" and e["name"] == "rollback"
               for e in doc["traceEvents"])


def test_flight_dump_rate_limited(tmp_path):
    trace_plane.configure(flight_recorder=True, flight_dir=str(tmp_path))
    assert trace_plane.flight_dump("send_failure") is not None
    # a failure storm must not write a bundle per event
    assert trace_plane.flight_dump("send_failure") is None
    assert trace_plane.flight_dump("manual", force=True) is not None


@pytest.mark.chaos
def test_chaos_crash_leaves_flight_bundle(tmp_path):
    """ISSUE acceptance: a chaos-injected client crash auto-dumps a
    replayable black-box bundle."""
    from fedml_tpu.cross_silo.chaos import run_chaos_drill

    r = run_chaos_drill(
        join_timeout_s=90.0, fault_drop_rate=0.0,
        fault_crash_rank=1, fault_crash_at_round=1,
        flight_recorder=True, flight_dir=str(tmp_path),
        trace_ship_spans=True)
    assert r.ok, r.summary()
    bundles = glob.glob(os.path.join(str(tmp_path), "flight_*_chaos_crash.json"))
    assert bundles, "crash did not leave a flight bundle"
    records = trace_plane.load_records(bundles[0])
    assert any(rec.get("kind") == "instant" and rec.get("name") == "crash"
               for rec in records)
    doc = trace_plane.export_chrome_trace(records)
    assert any(e.get("ph") == "X" for e in doc["traceEvents"])


def test_watchdog_rollback_dumps_flight_bundle(tmp_path):
    """Simulator watchdog rollback triggers the black-box dump."""
    from fedml_tpu.simulation import build_simulator

    args = fedml_tpu.init(config=dict(
        dataset="digits", model="lr", partition_method="homo",
        client_num_in_total=10, client_num_per_round=10, comm_round=8,
        learning_rate=0.3, epochs=1, batch_size=32,
        frequency_of_the_test=7, random_seed=0,
        attack_type="scale", attacker_ratio=0.2, attack_boost=50.0,
        watchdog_factor=1.5, watchdog_window=3, max_rollbacks=3,
        sanitize_z_thresh=1e6, rollback_z_thresh=3.0,
        flight_recorder=True, flight_dir=str(tmp_path),
    ))
    sim, apply_fn = build_simulator(args)
    hist = sim.run(apply_fn, log_fn=None)
    assert any(h["rollbacks"] > 0 for h in hist)
    bundles = glob.glob(
        os.path.join(str(tmp_path), "flight_*_watchdog_rollback.json"))
    assert bundles, "rollback did not leave a flight bundle"
    records = trace_plane.load_records(bundles[0])
    assert any(rec.get("kind") == "phase_record" for rec in records)


# --- spans-dropped counter (satellite) ---------------------------------------


def test_tracer_ring_eviction_counts_drops():
    telemetry.configure(enabled=True, reset=True, span_buffer=4)
    try:
        for i in range(6):
            with telemetry.get_tracer().span(f"s{i}"):
                pass
        assert telemetry.get_tracer().dropped == 2
        counters = telemetry.get_registry().snapshot()["counters"]
        assert counters.get("fedml_spans_dropped_total") == 2
        telemetry.get_tracer().clear()
        assert telemetry.get_tracer().dropped == 0
    finally:
        telemetry.configure(enabled=True, reset=True)


# --- CLI ---------------------------------------------------------------------


def _emit_jsonl(tmp_path):
    """Write a two-tenant JSONL sink file with spans, a phase record, an
    instant, and a clock offset."""
    jsonl = str(tmp_path / "run.jsonl")
    telemetry.configure(enabled=True, reset=True, jsonl_path=jsonl)
    trace_plane.configure(ship_spans=True)
    for tenant, rank in (("a", 0), ("a", 1), ("b", 0)):
        with telemetry.tenant_scope(tenant):
            ctx = telemetry.new_round_context(1)
            with telemetry.use_context(ctx):
                with telemetry.get_tracer().span("server.round", rank=rank):
                    pass
            trace_plane.on_round_record(
                {"round": 1, "round_time": 0.4,
                 "phases": {"dispatch": 0.25, "device": 0.15}}, rank=rank)
    with telemetry.tenant_scope("a"):
        trace_plane.record_instant("shed", attrs={"tenant": "a"})
        trace_plane.note_client_clock(1, 123.0)
    telemetry.flush()
    telemetry.configure(enabled=True, reset=True)  # close the sink
    return jsonl


def test_cli_telemetry_trace_two_tenant_export(tmp_path):
    from click.testing import CliRunner

    from fedml_tpu.cli.main import cli

    jsonl = _emit_jsonl(tmp_path)
    out = str(tmp_path / "round.trace.json")
    res = CliRunner().invoke(
        cli, ["telemetry", "trace", jsonl, "--out", out])
    assert res.exit_code == 0, res.output
    with open(out) as f:
        doc = json.load(f)
    events = doc["traceEvents"]
    procs = {e["args"]["name"] for e in events
             if e.get("ph") == "M" and e["name"] == "process_name"}
    assert procs == {"tenant:a", "tenant:b"}
    span_tracks = {(e["pid"], e["tid"]) for e in events
                   if e.get("ph") == "X" and e.get("cat") == "span"}
    assert len(span_tracks) == 3  # (a,0), (a,1), (b,0)
    for rec_pid, rec_tid in span_tracks:
        phase = [e for e in events if e.get("cat") == "phase"
                 and (e["pid"], e["tid"]) == (rec_pid, rec_tid)]
        assert sum(e["dur"] for e in phase) == pytest.approx(0.4 * 1e6)
    assert any(e.get("ph") == "i" and e["name"] == "shed" for e in events)
    # tenant filter drops tenant b entirely
    res = CliRunner().invoke(
        cli, ["telemetry", "trace", jsonl, "--out", out, "--tenant", "b"])
    assert res.exit_code == 0, res.output
    with open(out) as f:
        doc = json.load(f)
    assert all(e["args"]["name"] == "tenant:b" for e in doc["traceEvents"]
               if e.get("ph") == "M" and e["name"] == "process_name")


def test_cli_telemetry_summary_tenant_filter_and_drops(tmp_path):
    from click.testing import CliRunner

    from fedml_tpu.cli.main import cli

    jsonl = str(tmp_path / "run.jsonl")
    telemetry.configure(enabled=True, reset=True, jsonl_path=jsonl,
                        span_buffer=2)
    with telemetry.tenant_scope("a"):
        for _ in range(4):
            with telemetry.get_tracer().span("a.only"):
                pass
    with telemetry.tenant_scope("b"):
        with telemetry.get_tracer().span("b.only"):
            pass
    telemetry.flush()
    telemetry.configure(enabled=True, reset=True)
    res = CliRunner().invoke(cli, ["telemetry", "summary", jsonl])
    assert res.exit_code == 0, res.output
    assert "spans dropped (ring evictions)" in res.output
    res = CliRunner().invoke(
        cli, ["telemetry", "summary", jsonl, "--tenant", "a"])
    assert res.exit_code == 0, res.output
    assert "a.only" in res.output and "b.only" not in res.output


def test_filter_snapshot_scopes_series():
    reg = telemetry.get_registry()
    with telemetry.tenant_scope("a"):
        telemetry.scoped_registry("a").counter("fedml_rounds_total").inc(2)
    with telemetry.tenant_scope("b"):
        telemetry.scoped_registry("b").counter("fedml_rounds_total").inc(5)
    snap = telemetry.filter_snapshot(reg.snapshot(), "a")
    assert list(snap["counters"].values()) == [2]
