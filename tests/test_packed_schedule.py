"""Packed-lane cohort schedule: numeric parity with the even schedule.

The packed executor trains clients back-to-back inside one scan (param reset
at boundaries). Per-client training consumes the same batches in the same
order with the same per-(pos, step) RNG folds as the even path, so final
params must match up to f32 summation order.
"""

import numpy as np
import pytest

import jax

import fedml_tpu
from fedml_tpu.core.scheduler import lane_schedule
from fedml_tpu.simulation import build_simulator


def _args(**kw):
    base = dict(
        dataset="cifar10", model="lr", partition_method="hetero",
        partition_alpha=0.3, debug_small_data=True,
        client_num_in_total=12, client_num_per_round=6, comm_round=3,
        learning_rate=0.05, epochs=1, batch_size=16,
        frequency_of_the_test=3, random_seed=0,
    )
    base.update(kw)
    return fedml_tpu.init(config=base)


def _flat(params):
    return np.concatenate(
        [np.asarray(l, np.float64).ravel() for l in jax.tree.leaves(params)])


def test_lane_schedule_covers_exactly_once():
    counts = [5, 6, 8, 8, 8, 9, 10, 11, 12, 14]
    lanes, L = lane_schedule(counts, axis=1)
    seen = sorted(p for lane in lanes for p in lane)
    assert seen == list(range(10))
    loads = [sum(counts[p] for p in lane) for lane in lanes]
    assert max(loads) == L
    # padded work must beat the trivial one-client-per-lane schedule
    assert len(lanes) * L <= len(counts) * max(counts)


def test_lane_schedule_axis_multiple():
    lanes, L = lane_schedule([4, 4, 7, 9, 3], axis=4)
    assert len(lanes) % 4 == 0
    seen = sorted(p for lane in lanes for p in lane)
    assert seen == list(range(5))


def test_lane_schedule_fewer_clients_than_axis():
    lanes, L = lane_schedule([6, 3], axis=4)
    assert len(lanes) == 4
    assert sorted(p for lane in lanes for p in lane) == [0, 1]
    assert L >= 6


def test_packed_matches_even_sp():
    args_e = _args(cohort_schedule="even")
    sim_e, apply_e = build_simulator(args_e)
    assert not sim_e._packed
    hist_e = sim_e.run(apply_e, log_fn=None)

    args_p = _args(cohort_schedule="packed")
    sim_p, apply_p = build_simulator(args_p)
    assert sim_p._packed
    hist_p = sim_p.run(apply_p, log_fn=None)

    np.testing.assert_allclose(
        _flat(sim_e.params), _flat(sim_p.params), rtol=2e-4, atol=2e-6)
    assert hist_e[-1]["test_acc"] == pytest.approx(
        hist_p[-1]["test_acc"], abs=5e-3)
    assert hist_e[-1]["train_loss"] == pytest.approx(
        hist_p[-1]["train_loss"], rel=2e-3)


def test_packed_forced_lanes_matches_even():
    """packed_lanes pins the lane count (bench-swept knob; per-step cost is
    superlinear in lanes on real chips) without changing numerics."""
    args_e = _args(cohort_schedule="even")
    sim_e, apply_e = build_simulator(args_e)
    sim_e.run(apply_e, log_fn=None)

    for lanes in (1, 2):
        args_p = _args(cohort_schedule="packed", packed_lanes=lanes)
        sim_p, apply_p = build_simulator(args_p)
        assert sim_p._packed and sim_p.cfg.packed_lanes == lanes
        sim_p.run(apply_p, log_fn=None)
        np.testing.assert_allclose(
            _flat(sim_e.params), _flat(sim_p.params), rtol=2e-4, atol=2e-6)


def test_lane_schedule_force_lanes():
    from fedml_tpu.core.scheduler import lane_schedule

    lanes, L = lane_schedule([8, 8, 4, 4], axis=1, force_lanes=2)
    assert len(lanes) == 2 and L == 12
    # force_lanes is rounded up to a multiple of the mesh axis
    lanes, L = lane_schedule([8, 8, 4, 4], axis=2, force_lanes=3)
    assert len(lanes) == 4
    # and clamped to the cohort size
    lanes, _ = lane_schedule([8, 8], axis=1, force_lanes=16)
    assert len(lanes) == 2


def test_packed_matches_even_multiepoch():
    args_e = _args(cohort_schedule="even", epochs=2, comm_round=2)
    sim_e, apply_e = build_simulator(args_e)
    sim_e.run(apply_e, log_fn=None)

    args_p = _args(cohort_schedule="packed", epochs=2, comm_round=2)
    sim_p, apply_p = build_simulator(args_p)
    sim_p.run(apply_p, log_fn=None)

    np.testing.assert_allclose(
        _flat(sim_e.params), _flat(sim_p.params), rtol=2e-4, atol=2e-6)


def test_packed_on_mesh_matches_sp():
    from fedml_tpu.parallel import AXIS_CLIENT, MeshConfig, create_mesh

    mesh = create_mesh(MeshConfig(axes=((AXIS_CLIENT, 4),)),
                       devices=jax.devices()[:4])
    args_m = _args(cohort_schedule="packed")
    sim_m, apply_m = build_simulator(args_m, mesh=mesh)
    assert sim_m._packed
    hist_m = sim_m.run(apply_m, log_fn=None)

    args_s = _args(cohort_schedule="packed")
    sim_s, apply_s = build_simulator(args_s)
    hist_s = sim_s.run(apply_s, log_fn=None)

    np.testing.assert_allclose(
        _flat(sim_s.params), _flat(sim_m.params), rtol=2e-4, atol=2e-6)
    assert np.isfinite(hist_m[-1]["test_acc"])


@pytest.mark.slow
def test_packed_mesh_size_sweep_matches_sp():
    """VERDICT r3 #6: the packed path must compose at EVERY mesh size, with
    per-device lane shards scaling as devices grow — 2/4/8-device meshes
    all reproduce the SP result, and the lane grid G divides by the axis
    size (so each device owns G/axis lanes)."""
    from fedml_tpu.parallel import AXIS_CLIENT, MeshConfig, create_mesh

    args_s = _args(cohort_schedule="packed")
    sim_s, apply_s = build_simulator(args_s)
    sim_s.run(apply_s, log_fn=None)
    ref = _flat(sim_s.params)

    shard_lanes = {}
    for n in (2, 4, 8):
        mesh = create_mesh(MeshConfig(axes=((AXIS_CLIENT, n),)),
                           devices=jax.devices()[:n])
        args_m = _args(cohort_schedule="packed")
        sim_m, apply_m = build_simulator(args_m, mesh=mesh)
        assert sim_m._packed
        sim_m.run(apply_m, log_fn=None)
        np.testing.assert_allclose(ref, _flat(sim_m.params),
                                   rtol=2e-4, atol=2e-6)
        g, _ = sim_m._last_packed_shape
        assert g % n == 0, f"lane grid G={g} must divide mesh size {n}"
        shard_lanes[n] = g // n
    # per-device share shrinks (or stays) as the mesh grows
    assert shard_lanes[2] >= shard_lanes[4] >= shard_lanes[8] >= 1


def test_packed_flat_carry_matches_tree_carry():
    """cfg.packed_flat_carry (ravelled-vector lane carry — the v5e perf
    path) must be numerically interchangeable with the pytree carry,
    including momentum (opt-state reset at client boundaries rides the
    flat vector too) and the FedProx proximal term."""
    for extra in (dict(momentum=0.9),
                  dict(federated_optimizer="FedProx", fedprox_mu=0.1)):
        results = {}
        for flat in (False, True):
            args = _args(cohort_schedule="packed", comm_round=2,
                         packed_flat_carry=flat, **extra)
            sim, ap = build_simulator(args)
            assert sim._packed
            sim.run(ap, log_fn=None)
            results[flat] = _flat(sim.params)
        np.testing.assert_allclose(results[False], results[True],
                                   rtol=2e-5, atol=2e-7)


@pytest.mark.slow
def test_packed_flat_carry_conv_model_matches_tree():
    """Flat carry on a CONV model (the bench regime: ~many param leaves,
    the case the flat mode exists for) — parity vs tree carry, and the
    program must compile in reasonable time (regression guard for the
    unravel-in-scan path).

    Tolerance note: unlike the LR model (bit-close), conv backward
    accumulation orders differ under the re-fused flat program, and f32
    rounding differences amplify chaotically through GN/ReLU over the
    ~24 training steps — measured drift is ~6e-4 absolute after 2
    rounds, same class as the packed-vs-even tolerance."""
    results = {}
    for flat in (False, True):
        args = _args(dataset="cifar10", model="resnet8",
                     cohort_schedule="packed", comm_round=2, momentum=0.9,
                     client_num_in_total=4, client_num_per_round=3,
                     batch_size=8, packed_flat_carry=flat)
        sim, ap = build_simulator(args)
        assert sim._packed
        sim.run(ap, log_fn=None)
        results[flat] = _flat(sim.params)
    np.testing.assert_allclose(results[False], results[True],
                               rtol=1e-2, atol=2e-3)


def test_packed_with_momentum_and_prox():
    """Optimizer state reset at client boundaries: momentum must not leak
    across clients — parity vs the even path proves the reset is right."""
    for extra in (dict(momentum=0.9), dict(federated_optimizer="FedProx",
                                           fedprox_mu=0.1)):
        args_e = _args(cohort_schedule="even", comm_round=2, **extra)
        sim_e, a_e = build_simulator(args_e)
        sim_e.run(a_e, log_fn=None)
        args_p = _args(cohort_schedule="packed", comm_round=2, **extra)
        sim_p, a_p = build_simulator(args_p)
        assert sim_p._packed
        sim_p.run(a_p, log_fn=None)
        np.testing.assert_allclose(
            _flat(sim_e.params), _flat(sim_p.params), rtol=2e-4, atol=2e-6)


def test_packed_client_dropout_matches_even():
    """Dropped clients are excluded from lanes host-side; training result
    AND metric semantics (loss divided by the full cohort, dropped rows
    zero) must still match the even path, which masks them in-program."""
    args_e = _args(cohort_schedule="even", client_dropout_rate=0.5,
                   comm_round=3)
    sim_e, a_e = build_simulator(args_e)
    hist_e = sim_e.run(a_e, log_fn=None)

    args_p = _args(cohort_schedule="packed", client_dropout_rate=0.5,
                   comm_round=3)
    sim_p, a_p = build_simulator(args_p)
    hist_p = sim_p.run(a_p, log_fn=None)

    np.testing.assert_allclose(
        _flat(sim_e.params), _flat(sim_p.params), rtol=2e-4, atol=2e-6)
    for he, hp in zip(hist_e, hist_p):
        assert he["train_loss"] == pytest.approx(hp["train_loss"], rel=2e-3)


def test_packed_rejects_ineligible():
    with pytest.raises(ValueError, match="packed"):
        args = _args(cohort_schedule="packed",
                     federated_optimizer="SCAFFOLD")
        build_simulator(args)


def test_packed_checkpoint_resume_matches_uninterrupted(tmp_path):
    """Orbax resume composes with the packed executor: interrupted-at-3
    equals uninterrupted-6 exactly (round-indexed RNG/sampling)."""
    cfg = dict(
        dataset="cifar10", model="lr", partition_method="hetero",
        partition_alpha=0.3, debug_small_data=True,
        client_num_in_total=12, client_num_per_round=6, comm_round=6,
        learning_rate=0.05, epochs=1, batch_size=16,
        frequency_of_the_test=100, random_seed=0, cohort_schedule="packed",
    )
    args = fedml_tpu.init(config=dict(cfg))
    sim, _ = build_simulator(args)
    assert sim._packed
    sim.run(apply_fn=None, log_fn=None)
    full = _flat(sim.params)

    ck = str(tmp_path / "ck")
    args1 = fedml_tpu.init(config=dict(cfg, comm_round=3, checkpoint_dir=ck,
                                       checkpoint_frequency=1))
    sim1, _ = build_simulator(args1)
    sim1.run(apply_fn=None, log_fn=None)
    args2 = fedml_tpu.init(config=dict(cfg, comm_round=6, checkpoint_dir=ck,
                                       checkpoint_frequency=1))
    sim2, _ = build_simulator(args2)
    hist2 = sim2.run(apply_fn=None, log_fn=None)
    assert hist2[0]["round"] == 3
    np.testing.assert_allclose(full, _flat(sim2.params), atol=1e-5)
