"""Fused aggregation kernels: CPU interpret-mode bit-parity + the
double-buffered arena movement they ship with.

The contract under test is exactness, not tolerance: the fused
quantize+pack kernel must emit the same BYTES as the numpy wire codec, the
fused sanitize+Krum pass must reproduce the sequential
``sanitize_stacked`` → ``krum_aggregate`` bits, and a prefetch-overlapped
run must replay a synchronous run bit-for-bit. On CPU the kernels run in
interpret mode (opted in with ``interpret=True`` — production non-TPU
dispatch takes the bit-identical jnp reference instead), so every
assertion here is ``array_equal`` — any drift is a bug, not noise.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fedml_tpu.comm.codec import (
    _leaf_hash,
    build_stacked_roundtrip,
    pack_int4,
    parse_codec_spec,
    stochastic_quantize,
)
from fedml_tpu.core.robust import (
    fused_sanitize_krum,
    krum_aggregate,
    pairwise_sq_dists,
    sanitize_stacked,
)
from fedml_tpu.ops.pallas import (
    fused_gram,
    fused_quantize_pack,
    quant_shapes_ok,
    robust_shapes_ok,
)
from fedml_tpu.ops.pallas.agg_quant import row_keys
from fedml_tpu.ops.pallas.agg_robust import _reference_gram


def _eq(a, b, msg=""):
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b), err_msg=msg)


# ------------------------------------------------ fused quantize + pack

@pytest.mark.parametrize("bits", [8, 4])
@pytest.mark.parametrize("C,m", [(3, 256), (5, 700), (4, 257)])
def test_quantize_pack_bit_identical_to_wire(bits, C, m):
    """Kernel bytes == numpy wire codec bytes, row by row, incl. the odd-m
    nibble tail and partial trailing 256-chunks."""
    rng = np.random.default_rng(bits * 100 + C)
    vals = rng.standard_normal((C, m)).astype(np.float32)
    vals[0, :5] = 0.0  # a zero chunk prefix exercises the amax==0 scale
    seed, rnd = 13, 2
    cids = np.arange(10, 10 + C, dtype=np.uint32)
    lh = _leaf_hash("layer/w")
    packed, scales, dec = fused_quantize_pack(
        jnp.asarray(vals), bits, seed, jnp.uint32(rnd),
        jnp.asarray(cids), lh, interpret=True)
    for c in range(C):
        q, s, d = stochastic_quantize(vals[c], bits, seed, rnd,
                                      int(cids[c]), lh)
        wire = pack_int4(q) if bits == 4 else q
        _eq(packed[c], wire, f"row {c} packed bytes")
        _eq(scales[c], s, f"row {c} scales")
        _eq(dec[c], d, f"row {c} decode")


@pytest.mark.parametrize("bits", [8, 4])
def test_quantize_pack_kernel_matches_reference_path(bits):
    """interpret-mode pallas_call == the jittable jnp reference fallback."""
    rng = np.random.default_rng(7)
    vals = jnp.asarray(rng.standard_normal((6, 300)).astype(np.float32))
    cids = jnp.asarray(np.arange(6, dtype=np.uint32))
    args = (vals, bits, 3, jnp.uint32(1), cids, 99)
    pk, sk, dk = fused_quantize_pack(*args, use_kernel=True, interpret=True)
    pr, sr, dr = fused_quantize_pack(*args, use_kernel=False)
    _eq(pk, pr); _eq(sk, sr); _eq(dk, dr)


def test_quant_shapes_ok_bounds():
    assert quant_shapes_ok(8, 512)
    assert quant_shapes_ok(8, 255)  # sub-chunk cols pad up to one chunk
    assert not quant_shapes_ok(0, 256)
    assert not quant_shapes_ok(8, 0)


def test_row_keys_match_wire_key_chain():
    from fedml_tpu.comm.codec import stochastic_key

    cids = np.array([3, 77, 1024], np.uint32)
    h = np.asarray(row_keys(21, jnp.uint32(5), jnp.asarray(cids), 42))
    for i, c in enumerate(cids):
        assert int(h[i]) == stochastic_key(21, 5, int(c), 42)


# ------------------------------------------------ fused sanitize + Krum

def _poisoned_stack(C, seed=0, nan_row=1, boost_row=2):
    rng = np.random.default_rng(seed)
    upd = {
        "layer": {"w": rng.standard_normal((C, 40)).astype(np.float32)},
        "bias": rng.standard_normal((C, 7)).astype(np.float32),
    }
    upd["layer"]["w"][nan_row, 3] = np.nan
    upd["bias"][boost_row] *= 1e6
    return jax.tree.map(jnp.asarray, upd)


def test_gram_kernel_matches_reference():
    """Interpret-mode Pallas Gram tiles == pairwise_sq_dists' untiled vmap
    matmul, bit for bit, incl. the zero-padded partial block (C=10 -> 16).
    Input is nan_to_num'ed first — that's fused_gram's contract (the
    caller sanitizes, mirroring pairwise_sq_dists)."""
    rng = np.random.default_rng(1)
    flat_np = rng.standard_normal((10, 64)).astype(np.float32)
    flat_np[4, 0] = np.inf
    flat_np[7, 1] = np.nan
    flat = jnp.nan_to_num(jnp.asarray(flat_np))
    assert robust_shapes_ok(10, 64)
    g_k = fused_gram(flat, use_kernel=True, interpret=True)
    g_r = _reference_gram(flat)
    _eq(g_k, g_r, "gram")
    # the reference form IS pairwise_sq_dists' exact vmap expression
    _eq(g_r, jax.vmap(lambda r: flat @ r)(flat))


@pytest.mark.parametrize("use_kernel", [True, False])
@pytest.mark.parametrize("m,sample_weighted", [(1, False), (3, True)])
def test_fused_sanitize_krum_bit_identical(use_kernel, m, sample_weighted):
    """Fused pass == the simulator's sequential sanitize → krum calls, for
    every output: aggregate leaves, clean weights, quarantine, z, selection."""
    C = 12
    upd = _poisoned_stack(C)
    w = jnp.asarray(np.r_[np.full(C - 1, 8.0), 0.0].astype(np.float32))
    clean, cw, quar, z = sanitize_stacked(upd, w, z_thresh=6.0)
    agg0, sel0 = krum_aggregate(clean, cw, n_byz=2, m=m,
                                sample_weighted=sample_weighted)
    agg1, cw1, quar1, z1, sel1 = fused_sanitize_krum(
        upd, w, z_thresh=6.0, n_byz=2, m=m,
        sample_weighted=sample_weighted, use_kernel=use_kernel,
        interpret=True)
    _eq(cw1, cw); _eq(quar1, quar); _eq(z1, z); _eq(sel1, sel0)
    for (pa, a), (_, b) in zip(
            jax.tree_util.tree_leaves_with_path(agg1),
            jax.tree_util.tree_leaves_with_path(agg0)):
        _eq(a, b, f"agg leaf {pa}")


def test_fused_sanitize_krum_padded_cohort_valid_mask():
    """Padded cohorts: valid= threads through sanitize exactly as the
    unfused path (and Krum ignores it there too — asymmetry preserved)."""
    C, real = 16, 13
    upd = _poisoned_stack(C, seed=3)
    valid = np.arange(C) < real
    w_np = np.full(C, 4.0, np.float32)
    w_np[real:] = 0.0  # padding rows carry zero weight
    w = jnp.asarray(w_np)
    clean, cw, quar, z = sanitize_stacked(upd, w, z_thresh=6.0, valid=valid)
    agg0, sel0 = krum_aggregate(clean, cw, n_byz=1, m=2)
    agg1, cw1, quar1, z1, sel1 = fused_sanitize_krum(
        upd, w, z_thresh=6.0, n_byz=1, m=2, valid=valid)
    _eq(cw1, cw); _eq(quar1, quar); _eq(z1, z); _eq(sel1, sel0)
    for a, b in zip(jax.tree_util.tree_leaves(agg1),
                    jax.tree_util.tree_leaves(agg0)):
        _eq(a, b)


def test_fused_sanitize_krum_2device_mesh():
    """Sharded cohort axis (2 CPU devices): fused == unfused under the same
    out_shardings, bit for bit."""
    if jax.device_count() < 2:
        pytest.skip("needs >= 2 devices")
    from jax.sharding import Mesh

    from fedml_tpu.parallel.sharding import shard_along

    mesh = Mesh(np.asarray(jax.devices()[:2]), ("client",))
    C = 8
    upd = _poisoned_stack(C, seed=5)
    sh = jax.tree.map(lambda _: shard_along(mesh, "client", 0), upd)
    upd_dev = jax.tree.map(jax.device_put, upd, sh)
    w = jnp.asarray(np.full(C, 2.0, np.float32))
    clean, cw, quar, z = sanitize_stacked(upd_dev, w, out_shardings=sh)
    agg0, sel0 = krum_aggregate(clean, cw, n_byz=1, m=2)
    agg1, cw1, quar1, z1, sel1 = fused_sanitize_krum(
        upd_dev, w, n_byz=1, m=2, out_shardings=sh)
    _eq(cw1, cw); _eq(quar1, quar); _eq(sel1, sel0)
    for a, b in zip(jax.tree_util.tree_leaves(agg1),
                    jax.tree_util.tree_leaves(agg0)):
        _eq(a, b)


# ------------------------------------------------ codec fused encode path

def test_stacked_roundtrip_agg_kernels_bitparity():
    """build_stacked_roundtrip(agg_kernels=True) decodes the same bits as
    the default path — the wire-parity invariant of the fused encoder."""
    rng = np.random.default_rng(11)
    C = 4
    cids = jnp.asarray(np.array([5, 9, 2, 31], np.uint32))
    for spec in ("q8", "q4", "delta|topk:0.25|q4"):
        cs = parse_codec_spec(spec)
        rt0 = build_stacked_roundtrip(spec, seed=13)
        rt1 = build_stacked_roundtrip(spec, seed=13, agg_kernels=True)
        res0 = res1 = ({"w": jnp.zeros((C, 300), jnp.float32)}
                       if cs.topk is not None else ())
        for rnd in range(2):
            upd = {"w": jnp.asarray(
                rng.standard_normal((C, 300)).astype(np.float32))}
            dec0, res0 = rt0(upd, res0, cids, jnp.uint32(rnd))
            dec1, res1 = rt1(upd, res1, cids, jnp.uint32(rnd))
            for a, b in zip(jax.tree_util.tree_leaves((dec0, res0)),
                            jax.tree_util.tree_leaves((dec1, res1))):
                _eq(a, b, spec)


# ------------------------------------------------ partial-tile Krum dists

def test_pairwise_dists_partial_tile_sizes():
    """Any positive tile size works now — the last partial tile is padded
    with zero rows and trimmed (it used to be a hard ValueError)."""
    rng = np.random.default_rng(2)
    upd = {"w": jnp.asarray(rng.standard_normal((10, 33)).astype(np.float32))}
    base = pairwise_sq_dists(upd)
    for t in (3, 4, 7, 10, 16):
        _eq(pairwise_sq_dists(upd, tile_size=t), base, f"tile_size={t}")
    with pytest.raises(ValueError, match="must be positive"):
        pairwise_sq_dists(upd, tile_size=0)


# ------------------------------------------------ double-buffered arena

def _arena(capacity=8, mesh=None):
    from fedml_tpu.simulation.client_store import ClientStateArena

    proto = {"c": jnp.zeros((3,), jnp.float32), "n": jnp.zeros((), jnp.int32)}
    return ClientStateArena(proto, capacity, mesh=mesh)


def test_put_take_matches_scatter_then_gather():
    """put_take == scatter followed by gather, including an overlapping
    client that must come back with its freshly written row."""
    a1, a2 = _arena(), _arena()
    first = [1, 2, 3]
    for a in (a1, a2):
        a.gather(first)
    rows = {"c": jnp.asarray(np.arange(9, dtype=np.float32).reshape(3, 3)),
            "n": jnp.asarray(np.array([7, 8, 9], np.int32))}
    nxt = [3, 4, 4, 5]  # 3 overlaps the put cohort; 4 repeats (padding)
    got = a1.put_take(first, rows, nxt)
    assert got is not None
    a2.scatter(first, rows)
    want = a2.gather(nxt)
    for x, y in zip(jax.tree_util.tree_leaves(got),
                    jax.tree_util.tree_leaves(want)):
        _eq(x, y)
    _eq(got["n"][0], 9)  # client 3's row is the POST-scatter value


def test_put_take_protect_aborts_without_mutation():
    """When the next cohort cannot fit without evicting a pending-scatter
    client, put_take refuses and leaves every slot untouched."""
    a = _arena(capacity=4)
    cur = [0, 1, 2, 3]
    a.gather(cur)
    rows = {"c": jnp.ones((4, 3), jnp.float32),
            "n": jnp.asarray(np.arange(4, dtype=np.int32))}
    before = dict(a._slot_of)
    got = a.put_take(cur, rows, [0, 1, 9, 10])  # 9,10 would evict 2 or 3
    assert got is None
    assert a._slot_of == before and a.spilled_count == 0
    a.scatter(cur, rows)  # the fallback path still works afterwards
    _eq(a.state_of(3)["n"], 3)


def test_put_take_rejects_duplicate_put_ids():
    a = _arena()
    a.gather([1, 2])
    rows = {"c": jnp.zeros((2, 3), jnp.float32),
            "n": jnp.zeros((2,), jnp.int32)}
    with pytest.raises(ValueError, match="unique"):
        a.put_take([1, 1], rows, [2])


def test_prefetcher_peek_is_nonconsuming():
    import time

    from fedml_tpu.simulation.prefetch import RoundPrefetcher

    with RoundPrefetcher(lambda r: f"item{r}", range(3), depth=2) as pf:
        deadline = time.monotonic() + 5.0
        while pf.peek(0) is None and time.monotonic() < deadline:
            time.sleep(0.005)
        assert pf.peek(0) == "item0"
        assert pf.peek(1) is None  # head is round 0, not 1
        assert pf.get(0) == "item0"  # peek did not consume it
        assert pf.get(1) == "item1"
    assert pf.peek(2) is None  # closed: peek is None, never raises


def test_prefetch_overlap_run_is_bit_identical(tmp_path):
    """End to end: a prefetch-overlapped SCAFFOLD run (put_take movement
    engaged) replays the synchronous run bit for bit — history and params."""
    import fedml_tpu
    from fedml_tpu.data.federated import ArrayPair, build_federated_data
    from fedml_tpu.simulation import build_simulator

    pool, spc = 24, 4
    rng = np.random.default_rng(0)
    n = pool * spc
    y = (np.arange(n) % 2).astype(np.int64)
    x = (rng.normal(size=(n, 8)).astype(np.float32)
         + 2.0 * y[:, None].astype(np.float32))
    fed = build_federated_data(
        ArrayPair(x, y), ArrayPair(x[:16], y[:16]),
        {c: list(range(c * spc, (c + 1) * spc)) for c in range(pool)}, 2)

    def run(prefetch):
        args = fedml_tpu.init(config=dict(
            dataset="blobs", model="lr", client_num_in_total=pool,
            client_num_per_round=8, comm_round=4, learning_rate=0.1,
            epochs=1, batch_size=spc, frequency_of_the_test=10_000,
            random_seed=0, federated_optimizer="SCAFFOLD",
            prefetch=prefetch, prefetch_depth=2))
        sim, _ = build_simulator(args, fed_data=fed)
        hist = sim.run(apply_fn=None, log_fn=None)
        return sim, hist

    s0, h0 = run(False)
    s1, h1 = run(True)
    assert any(r["phases"].get("state_move", 0) > 0 for r in h1), \
        "double-buffered movement never engaged"
    for r0, r1 in zip(h0, h1):
        assert r0["train_loss"] == r1["train_loss"]
    for a, b in zip(jax.tree_util.tree_leaves(s0.params),
                    jax.tree_util.tree_leaves(s1.params)):
        _eq(a, b)


# ------------------------------------------------ native stale-.so guard

def test_native_embedded_hash_matches_source():
    from fedml_tpu import native

    if not native.native_available():
        pytest.skip("no native toolchain in this environment")
    lib = native.get_lib()
    import ctypes

    fn = lib.fedml_native_src_hash
    fn.restype = ctypes.c_char_p
    embedded = fn().decode().split("=", 1)[1]
    assert embedded == native._src_hash()


def test_native_hash_mismatch_falls_back(monkeypatch):
    from fedml_tpu import native

    class _FakeLib:
        pass  # no fedml_native_src_hash symbol: pre-hash binary

    monkeypatch.setattr(native, "_hash_warned", False)
    assert not native._hash_ok(_FakeLib())
    assert native._hash_warned  # warned exactly once, then silent
    assert not native._hash_ok(_FakeLib())
