"""End-to-end smoke: SP simulator trains and improves (reference test strategy:
smoke runs of real examples, SURVEY.md §4 — ``tests/smoke_test/simulation_sp``)."""

import numpy as np
import pytest

import fedml_tpu
from fedml_tpu.simulation import build_simulator


def small_args(**over):
    base = dict(
        dataset="mnist", model="lr", debug_small_data=True,
        client_num_in_total=20, client_num_per_round=8, comm_round=3,
        learning_rate=0.1, epochs=1, batch_size=32,
        frequency_of_the_test=2, random_seed=0, partition_method="hetero",
        partition_alpha=0.5,
    )
    base.update(over)
    return fedml_tpu.init(config=base)


def test_sp_fedavg_mnist_lr_runs_and_learns():
    args = small_args(comm_round=6)
    sim, apply_fn = build_simulator(args)
    hist = sim.run(apply_fn, log_fn=None)
    assert len(hist) == 6
    # synthetic mnist-like data is separable; LR should beat chance quickly
    assert hist[-1]["test_acc"] > 0.3
    assert hist[-1]["train_loss"] < hist[0]["train_loss"]


def test_sp_deterministic_across_runs():
    args = small_args(comm_round=2)
    sim1, f1 = build_simulator(args)
    h1 = sim1.run(f1, log_fn=None)
    args2 = small_args(comm_round=2)
    sim2, f2 = build_simulator(args2)
    h2 = sim2.run(f2, log_fn=None)
    assert h1[-1]["train_loss"] == pytest.approx(h2[-1]["train_loss"], rel=1e-5)


@pytest.mark.parametrize("opt", ["FedOpt", "FedProx", "FedNova", "SCAFFOLD"])
def test_sp_optimizer_variants_run(opt):
    args = small_args(federated_optimizer=opt, comm_round=2, server_lr=0.5)
    sim, apply_fn = build_simulator(args)
    hist = sim.run(apply_fn, log_fn=None)
    assert len(hist) == 2
    assert np.isfinite(hist[-1]["train_loss"])
