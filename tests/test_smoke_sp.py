"""End-to-end smoke: SP simulator trains and improves (reference test strategy:
smoke runs of real examples, SURVEY.md §4 — ``tests/smoke_test/simulation_sp``)."""

import numpy as np
import pytest

import fedml_tpu
from fedml_tpu.simulation import build_simulator


def small_args(**over):
    base = dict(
        dataset="mnist", model="lr", debug_small_data=True,
        client_num_in_total=20, client_num_per_round=8, comm_round=3,
        learning_rate=0.1, epochs=1, batch_size=32,
        frequency_of_the_test=2, random_seed=0, partition_method="hetero",
        partition_alpha=0.5,
    )
    base.update(over)
    return fedml_tpu.init(config=base)


def test_sp_fedavg_mnist_lr_runs_and_learns():
    args = small_args(comm_round=6)
    sim, apply_fn = build_simulator(args)
    hist = sim.run(apply_fn, log_fn=None)
    assert len(hist) == 6
    # synthetic mnist-like data is separable; LR should beat chance quickly
    assert hist[-1]["test_acc"] > 0.3
    assert hist[-1]["train_loss"] < hist[0]["train_loss"]


def test_sp_deterministic_across_runs():
    args = small_args(comm_round=2)
    sim1, f1 = build_simulator(args)
    h1 = sim1.run(f1, log_fn=None)
    args2 = small_args(comm_round=2)
    sim2, f2 = build_simulator(args2)
    h2 = sim2.run(f2, log_fn=None)
    assert h1[-1]["train_loss"] == pytest.approx(h2[-1]["train_loss"], rel=1e-5)


@pytest.mark.parametrize("opt", ["FedOpt", "FedProx", "FedNova", "SCAFFOLD"])
def test_sp_optimizer_variants_run(opt):
    args = small_args(federated_optimizer=opt, comm_round=2, server_lr=0.5)
    sim, apply_fn = build_simulator(args)
    hist = sim.run(apply_fn, log_fn=None)
    assert len(hist) == 2
    assert np.isfinite(hist[-1]["train_loss"])


def test_batchnorm_resnet_trains_and_averages_stats():
    """norm='batch' resnet20: batch_stats thread through the local update and
    are federated-averaged in the delta (reference fedavg_api.py:163-170)."""
    import jax

    args = fedml_tpu.init(config=dict(
        dataset="cifar10", model="resnet8", norm="batch",
        debug_small_data=True, client_num_in_total=4, client_num_per_round=2,
        comm_round=2, learning_rate=0.05, epochs=1, batch_size=8,
        frequency_of_the_test=1, random_seed=0,
    ))
    sim, apply_fn = build_simulator(args)
    assert "batch_stats" in sim.params
    stats_before = jax.tree.map(lambda x: np.asarray(x).copy(),
                                sim.params["batch_stats"])
    hist = sim.run(apply_fn, log_fn=None)
    assert len(hist) == 2 and np.isfinite(hist[-1]["train_loss"])
    # running stats must have moved off their init values
    moved = jax.tree.leaves(jax.tree.map(
        lambda a, b: float(np.abs(np.asarray(a) - b).max()),
        sim.params["batch_stats"], stats_before,
    ))
    assert max(moved) > 1e-6
    finite = jax.tree.leaves(jax.tree.map(
        lambda a: bool(np.isfinite(np.asarray(a)).all()),
        sim.params["batch_stats"],
    ))
    assert all(finite)


def test_batchnorm_fedopt_splits_server_update():
    """FedOpt + norm='batch': server optimizer touches params only; running
    stats are plainly averaged and stay finite/positive-variance."""
    import jax

    args = fedml_tpu.init(config=dict(
        dataset="cifar10", model="resnet8", norm="batch",
        federated_optimizer="FedOpt", server_optimizer="adam", server_lr=0.1,
        debug_small_data=True, client_num_in_total=4, client_num_per_round=2,
        comm_round=3, learning_rate=0.05, epochs=1, batch_size=8,
        frequency_of_the_test=10, random_seed=0,
    ))
    sim, apply_fn = build_simulator(args)
    hist = sim.run(apply_fn, log_fn=None)
    assert np.isfinite(hist[-1]["train_loss"])
    for path, leaf in jax.tree_util.tree_leaves_with_path(
        sim.params["batch_stats"]
    ):
        arr = np.asarray(leaf)
        assert np.isfinite(arr).all()
        if "var" in str(path):
            assert (arr > 0).all(), f"negative running variance at {path}"


def test_batchnorm_rejected_for_stats_corrupting_optimizers():
    args = fedml_tpu.init(config=dict(
        dataset="cifar10", model="resnet20", norm="batch",
        federated_optimizer="FedNova", debug_small_data=True,
        client_num_in_total=2, client_num_per_round=2, comm_round=1,
        learning_rate=0.05, batch_size=8, random_seed=0,
    ))
    with pytest.raises(ValueError, match="norm='batch'"):
        build_simulator(args)


@pytest.mark.parametrize("sopt", ["adam", "yogi", "adagrad"])
def test_fedopt_adaptive_server_optimizers_learn(sopt):
    """The adaptive federated-optimization trio (Reddi et al.) on the
    server pseudo-gradient — each must actually learn, not just run.
    Adagrad's accumulating denominator wants a larger server lr."""
    args = small_args(federated_optimizer="FedOpt", server_optimizer=sopt,
                      server_lr=0.3 if sopt == "adagrad" else 0.05,
                      comm_round=8, frequency_of_the_test=8)
    sim, apply_fn = build_simulator(args)
    hist = sim.run(apply_fn, log_fn=None)
    assert hist[-1]["test_acc"] > 0.8, (sopt, hist[-1])


def test_fedopt_unknown_server_optimizer_rejected():
    args = small_args(federated_optimizer="FedOpt", server_optimizer="lamb")
    with pytest.raises(ValueError, match="server_optimizer"):
        build_simulator(args)


def test_fedopt_server_optimizer_case_and_none_tolerant():
    """YAML-sourced values arrive stringified: 'Adam' and None must keep
    working (None falls back to the sgd default)."""
    for sopt in ("Adam", "None"):
        args = small_args(federated_optimizer="FedOpt",
                          server_optimizer=sopt, comm_round=1,
                          frequency_of_the_test=10)
        sim, apply_fn = build_simulator(args)
        hist = sim.run(apply_fn, log_fn=None)
        assert np.isfinite(hist[-1]["train_loss"])
