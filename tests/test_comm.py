"""Comm plane: message codec, loopback round protocol, gRPC backend, topology."""

import threading
import time

import numpy as np
import pytest

from fedml_tpu.comm import (
    AsymmetricTopologyManager,
    LoopbackCommManager,
    LoopbackHub,
    Message,
    SymmetricTopologyManager,
    ring_mixing_matrix,
)
from fedml_tpu.comm.managers import ClientManager, ServerManager


def test_message_codec_roundtrip_arrays():
    msg = Message(type=3, sender_id=1, receiver_id=0)
    params = {
        "dense/kernel": np.arange(12, dtype=np.float32).reshape(3, 4),
        "dense/bias": np.zeros(4, dtype=np.float32),
    }
    msg.add_params(Message.MSG_ARG_KEY_MODEL_PARAMS, params)
    msg.add_params(Message.MSG_ARG_KEY_NUM_SAMPLES, 128)
    out = Message.from_bytes(msg.to_bytes())
    assert out.get_type() == 3
    assert out.get_sender_id() == 1 and out.get_receiver_id() == 0
    got = out.get(Message.MSG_ARG_KEY_MODEL_PARAMS)
    np.testing.assert_array_equal(got["dense/kernel"], params["dense/kernel"])
    assert got["dense/kernel"].dtype == np.float32
    assert out.get(Message.MSG_ARG_KEY_NUM_SAMPLES) == 128


def test_message_codec_bf16_via_jax():
    import jax.numpy as jnp
    import ml_dtypes

    msg = Message(type=1)
    msg.add_params("w", np.asarray(jnp.full((2, 2), 1.5, jnp.bfloat16)))
    out = Message.from_bytes(msg.to_bytes())
    got = out.get("w")
    assert got.shape == (2, 2)
    # dtype must survive as a real bfloat16, usable in arithmetic — not an
    # opaque void ('|V2') view (ADVICE r1: bf16 params over loopback/gRPC)
    assert got.dtype == np.dtype(ml_dtypes.bfloat16)
    np.testing.assert_allclose(got.astype(np.float32), 1.5)
    assert (got + got).astype(np.float32).sum() == 12.0


MSG_INIT, MSG_MODEL, MSG_DONE = 1, 3, 99


class _EchoServer(ServerManager):
    """Minimal round FSM: send INIT to all clients, collect one MODEL from
    each, then stop everyone."""

    def __init__(self, args, size, hub):
        super().__init__(args, rank=0, size=size, backend="LOOPBACK", hub=hub)
        self.received = {}
        self.hub = hub

    def start_round(self):
        for rank in range(1, self.size):
            m = Message(MSG_INIT, 0, rank)
            m.add_params(Message.MSG_ARG_KEY_MODEL_PARAMS, {"w": np.ones(3)})
            self.send_message(m)

    def register_message_receive_handlers(self):
        self.register_message_receive_handler(MSG_MODEL, self._on_model)

    def _on_model(self, msg):
        self.received[msg.get_sender_id()] = msg.get(Message.MSG_ARG_KEY_MODEL_PARAMS)
        if len(self.received) == self.size - 1:
            for rank in range(1, self.size):
                self.send_message(Message(MSG_DONE, 0, rank))
            self.finish()


class _EchoClient(ClientManager):
    def __init__(self, args, rank, size, hub):
        super().__init__(args, rank=rank, size=size, backend="LOOPBACK", hub=hub)

    def register_message_receive_handlers(self):
        self.register_message_receive_handler(MSG_INIT, self._on_init)
        self.register_message_receive_handler(MSG_DONE, lambda m: self.finish())

    def _on_init(self, msg):
        w = msg.get(Message.MSG_ARG_KEY_MODEL_PARAMS)["w"]
        reply = Message(MSG_MODEL, self.rank, 0)
        reply.add_params(Message.MSG_ARG_KEY_MODEL_PARAMS, {"w": w * self.rank})
        self.send_message(reply)


def test_loopback_round_protocol():
    hub = LoopbackHub()
    size = 4
    server = _EchoServer(None, size, hub)
    clients = [_EchoClient(None, r, size, hub) for r in range(1, size)]
    threads = [threading.Thread(target=c.run, daemon=True) for c in clients]
    for t in threads:
        t.start()
    server.start_round()
    server.run()  # blocks until all models received
    for t in threads:
        t.join(timeout=10)
    assert set(server.received) == {1, 2, 3}
    np.testing.assert_array_equal(server.received[2]["w"], 2 * np.ones(3))


def test_grpc_backend_send_receive():
    grpc = pytest.importorskip("grpc")
    del grpc
    from fedml_tpu.comm.grpc_backend import GRPCCommManager

    received = []

    class _Obs:
        def receive_message(self, t, m):
            received.append((t, m.get("x")))

    m0 = GRPCCommManager(rank=0, size=2, base_port=18890)
    m1 = GRPCCommManager(rank=1, size=2, base_port=18890)
    try:
        # send BEFORE the receiver registers observers or starts its loop:
        # the inbox must buffer it (a real startup race, caught in review)
        msg = Message(7, 0, 1)
        msg.add_params("x", np.full((1000,), 3.0, np.float32))
        m0.send_message(msg)
        m1.add_observer(_Obs())
        t = threading.Thread(target=m1.handle_receive_message, daemon=True)
        t.start()
        deadline = time.time() + 10
        while not received and time.time() < deadline:
            time.sleep(0.01)
        assert received and received[0][0] == 7
        np.testing.assert_array_equal(received[0][1], np.full((1000,), 3.0, np.float32))
        # received arrays must be writable (handlers mutate in place)
        received[0][1][0] = 0.0
    finally:
        m0.stop_receive_message()
        m1.stop_receive_message()
        t.join(timeout=5)


def test_symmetric_topology_mixing_matrix():
    tm = SymmetricTopologyManager(8, neighbor_num=2, seed=0)
    tm.generate_topology()
    w = tm.topology
    assert w.shape == (8, 8)
    np.testing.assert_allclose(w, w.T)
    np.testing.assert_allclose(w.sum(axis=1), np.ones(8), atol=1e-9)
    assert 0 in tm.get_in_neighbor_idx_list(1)


def test_asymmetric_topology_row_stochastic():
    tm = AsymmetricTopologyManager(6, neighbor_num=2, seed=1)
    tm.generate_topology()
    np.testing.assert_allclose(tm.topology.sum(axis=1), np.ones(6), atol=1e-9)


def test_ring_mixing_matrix_doubly_stochastic():
    w = ring_mixing_matrix(5)
    np.testing.assert_allclose(w.sum(axis=0), np.ones(5), atol=1e-9)
    np.testing.assert_allclose(w.sum(axis=1), np.ones(5), atol=1e-9)
