"""Multi-tenant control plane: admission verdicts, deficit-round-robin fair
scheduling, per-tenant telemetry/checkpoint/numerics isolation, bounded
check-in overload, and the chaos isolation drill (one tenant's server dies
and recovers from its own RoundStateStore while the other tenant's run never
notices)."""

import math
import threading

import numpy as np
import pytest

import fedml_tpu
from fedml_tpu.core import telemetry
from fedml_tpu.core.tenancy import (
    AdmissionVerdict,
    CheckinQueue,
    DeficitRoundRobinScheduler,
    JobRegistry,
    ResourceEnvelope,
)

estimate_device_memory_bytes = ResourceEnvelope.estimate_device_memory_bytes
from fedml_tpu.simulation import (
    MultiTenantSimDriver,
    SimulatorSingleProcess,
    TenantJob,
)


@pytest.fixture(autouse=True)
def _fresh_telemetry():
    telemetry.configure(enabled=True, reset=True)
    yield
    telemetry.configure(enabled=True, reset=True)


def _counters():
    return telemetry.get_registry().snapshot()["counters"]


def _env(tenant, model_bytes=1000, cohort=4, **kw):
    return ResourceEnvelope(tenant=tenant, cohort_size=cohort,
                            model_bytes=model_bytes, **kw)


# --- admission ---------------------------------------------------------------


def test_admission_envelope_estimates_device_memory():
    env = _env("a", model_bytes=100, cohort=4)
    assert env.device_memory_bytes == estimate_device_memory_bytes(4, 100)
    assert env.device_memory_bytes == 100 * (3 + 4)


def test_admission_admit_queue_reject_verdicts():
    # capacity fits exactly two 100-byte-model/4-client envelopes
    cap = 2 * estimate_device_memory_bytes(4, 100)
    reg = JobRegistry(capacity_bytes=cap, max_concurrent=8, max_queue=1)

    a = reg.admit(_env("a", 100))
    b = reg.admit(_env("b", 100))
    assert a.admitted and b.admitted
    assert a.decision == "admit"
    assert reg.available_bytes() == 0

    # never fits, even on an empty registry: typed reject with the numbers
    giant = reg.admit(_env("giant", 10 * cap))
    assert giant.rejected and not giant.admitted
    assert giant.requested_bytes > giant.capacity_bytes
    assert "giant" in giant.summary()

    # fits-but-not-now: queued, with a position
    c = reg.admit(_env("c", 100))
    assert c.queued and c.queue_position == 0

    # bounded queue: the next one is turned away, not buffered forever
    d = reg.admit(_env("d", 100))
    assert d.rejected

    # duplicate tenant name is a reject regardless of capacity
    dup = reg.admit(_env("a", 1))
    assert dup.rejected

    # releasing a running job promotes the queue head
    promoted = reg.release("a")
    assert [v.tenant for v in promoted] == ["c"]
    assert all(isinstance(v, AdmissionVerdict) and v.admitted
               for v in promoted)
    assert sorted(reg.active_tenants()) == ["b", "c"]

    # every verdict was counted, split by decision
    cs = _counters()
    assert cs.get("fedml_admissions_total{decision=admit,tenant=a}") == 1
    assert cs.get("fedml_admissions_total{decision=reject,tenant=giant}") == 1
    assert cs.get("fedml_admissions_total{decision=queue,tenant=c}") == 1


# --- fair scheduling ---------------------------------------------------------


def test_drr_fair_share_converges_for_unequal_round_costs():
    sched = DeficitRoundRobinScheduler(quantum=1.0)
    sched.register("cheap", round_cost=1.0)
    sched.register("pricey", round_cost=5.0)
    for _ in range(600):
        t = sched.next_tenant()
        assert t is not None
        sched.charge(t, 1.0 if t == "cheap" else 5.0)
    served = {t: s["served"] for t, s in sched.stats().items()}
    # equal priorities -> equal long-run service, regardless of unit cost
    assert served["cheap"] > 0 and served["pricey"] > 0
    assert abs(served["cheap"] / served["pricey"] - 1.0) < 0.05


def test_drr_priority_weights_service_proportionally():
    sched = DeficitRoundRobinScheduler(quantum=1.0)
    sched.register("gold", round_cost=1.0, priority=3.0)
    sched.register("bronze", round_cost=1.0, priority=1.0)
    for _ in range(400):
        t = sched.next_tenant()
        sched.charge(t, 1.0)
    served = {t: s["served"] for t, s in sched.stats().items()}
    assert served["gold"] / served["bronze"] == pytest.approx(3.0, rel=0.1)


def test_drr_demotes_persistently_over_budget_tenant():
    sched = DeficitRoundRobinScheduler(quantum=1.0, demote_factor=0.5,
                                       over_budget_factor=2.0, demote_after=3)
    sched.register("hog", round_cost=1.0)
    sched.register("meek", round_cost=1.0)
    p0 = sched.priority("hog")
    for _ in range(20):
        t = sched.next_tenant()
        # the hog consistently burns 4x its declared budget
        sched.charge(t, 4.0 if t == "hog" else 1.0)
    assert sched.priority("hog") < p0
    assert sched.priority("meek") == pytest.approx(1.0)
    assert sched.demotions("hog") >= 1
    assert _counters().get(
        "fedml_tenant_demotions_total{tenant=hog}", 0) >= 1


# --- overload: bounded check-in queue ---------------------------------------


def test_checkin_queue_sheds_when_full_and_accounting_closes():
    q = CheckinQueue(maxsize=8)
    for i in range(20):
        q.offer(b"x", tenant="t%d" % (i % 2))
    stats = q.stats()
    assert stats["offered"] == 20
    assert stats["accepted"] == 8
    assert stats["shed"] == 12
    assert stats["offered"] == stats["accepted"] + stats["shed"]
    assert stats["max_depth"] <= stats["maxsize"] == 8

    # shedding is visible per tenant in the registry
    cs = _counters()
    shed = sum(v for k, v in cs.items()
               if k.startswith("fedml_checkins_shed_total{"))
    assert shed == 12
    assert cs.get("fedml_checkins_shed_total{tenant=t0}", 0) > 0
    assert cs.get("fedml_checkins_shed_total{tenant=t1}", 0) > 0

    # draining reopens capacity
    assert q.poll() == b"x"
    q.offer(b"y", tenant="t0")
    assert q.stats()["accepted"] == 9


def test_checkin_queue_sheds_by_reason():
    q = CheckinQueue(maxsize=4)
    # the caller's registry can refuse a device before the queue is asked
    assert q.offer(b"dup", tenant="t0", admissible=False) is False
    for _ in range(6):
        q.offer(b"x", tenant="t0")
    stats = q.stats()
    assert stats["shed_inadmissible"] == 1
    assert stats["shed_queue_full"] == 2
    assert stats["shed"] == stats["shed_queue_full"] \
        + stats["shed_inadmissible"]
    # per-reason shed counters (what `telemetry summary` breaks down)
    cs = _counters()
    assert cs.get("fedml_shed_total{reason=inadmissible,tenant=t0}") == 1
    assert cs.get("fedml_shed_total{reason=queue_full,tenant=t0}") == 2
    # reason totals reconcile with the legacy per-tenant shed counter
    assert sum(v for k, v in cs.items()
               if k.startswith("fedml_shed_total{")) \
        == cs["fedml_checkins_shed_total{tenant=t0}"]


def test_checkin_queue_offer_many_accounting_matches_per_offer():
    # one arrival wave through the batched edge ...
    q_batch = CheckinQueue(maxsize=4)
    adm = [True, False, True, True, False, True, True, True]
    out = q_batch.offer_many(list(range(8)), tenant="t0", admissible=adm)
    assert out == {"accepted": 4, "shed_queue_full": 2,
                   "shed_inadmissible": 2}
    # ... is indistinguishable from the same wave offered one at a time
    q_solo = CheckinQueue(maxsize=4)
    for i, a in enumerate(adm):
        q_solo.offer(i, tenant="t0", admissible=a)
    assert q_batch.stats() == q_solo.stats()
    # inadmissible sheds never consumed queue room
    assert [q_batch.poll() for _ in range(4)] == [0, 2, 3, 5]
    # telemetry saw both edges identically (batch + solo = 2x each count)
    cs = _counters()
    assert cs["fedml_checkins_accepted_total{tenant=t0}"] == 8
    assert cs["fedml_shed_total{reason=queue_full,tenant=t0}"] == 4
    assert cs["fedml_shed_total{reason=inadmissible,tenant=t0}"] == 4


# --- telemetry isolation -----------------------------------------------------


def test_tenant_scope_labels_metrics_and_scoped_registry_filters():
    reg = telemetry.get_registry()
    with telemetry.tenant_scope("acme"):
        reg.counter("fedml_widgets_total").inc(3)
    with telemetry.tenant_scope("globex"):
        reg.counter("fedml_widgets_total").inc(4)
    reg.counter("fedml_widgets_total").inc(5)  # unscoped

    cs = _counters()
    assert cs["fedml_widgets_total{tenant=acme}"] == 3
    assert cs["fedml_widgets_total{tenant=globex}"] == 4
    assert cs["fedml_widgets_total"] == 5

    scoped = telemetry.scoped_registry("acme")
    snap = scoped.snapshot()["counters"]
    assert snap == {"fedml_widgets_total{tenant=acme}": 3}
    # writes through the facade are labeled without entering the scope
    scoped.counter("fedml_widgets_total").inc(2)
    assert _counters()["fedml_widgets_total{tenant=acme}"] == 5


# --- the multi-tenant driver -------------------------------------------------


_TIMING_KEYS = frozenset(
    ("round_time", "dispatch_time", "phases", "pack_time", "pack_wait",
     "overlap"))


def _strip_timing(history):
    return [{k: v for k, v in rec.items() if k not in _TIMING_KEYS}
            for rec in history]


def _job_cfg(seed, clients, rounds=2, batch=8):
    return dict(dataset="mnist", model="lr", debug_small_data=True,
                client_num_in_total=clients, client_num_per_round=clients,
                comm_round=rounds, learning_rate=0.1, epochs=1,
                batch_size=batch, frequency_of_the_test=1, random_seed=seed,
                prefetch=False)


def test_eight_concurrent_jobs_bit_identical_to_solo_with_exact_phases():
    """The acceptance drill: 8 heterogeneous jobs interleaved over one mesh
    must each (a) run to completion, (b) keep a per-round phase breakdown —
    including the tenant_wait the scheduler imposed — that sums exactly to
    that round's round_time, and (c) produce a history bit-identical to the
    same config run solo (timing fields aside)."""
    specs = {f"t{i}": _job_cfg(seed=i, clients=2 + (i % 3),
                               rounds=1 + (i % 2), batch=4 + 4 * (i % 2))
             for i in range(8)}

    solo = {}
    for name, cfg in specs.items():
        sim = SimulatorSingleProcess(fedml_tpu.init(config=dict(cfg)))
        solo[name] = sim.sim.run(sim.apply_fn, log_fn=None)

    driver = MultiTenantSimDriver(
        [TenantJob(name, cfg, priority=1.0 + (i % 2))
         for i, (name, cfg) in enumerate(specs.items())],
        capacity_bytes=2 << 30, max_concurrent=8)
    results = driver.run()

    assert sorted(results) == sorted(specs)
    for name, res in results.items():
        assert res.ok, res.summary()
        assert res.verdict.admitted
        assert len(res.history) == specs[name]["comm_round"]
        for rec in res.history:
            phases = rec["phases"]
            assert "tenant_wait" in phases
            assert math.isclose(sum(phases.values()), rec["round_time"],
                                rel_tol=1e-6, abs_tol=1e-9)
        assert _strip_timing(res.history) == _strip_timing(solo[name])

    # per-tenant phase telemetry stayed isolated: every job's round count
    # shows up under its own label
    snap = telemetry.get_registry().snapshot()["histograms"]
    for name in specs:
        h = snap.get("fedml_round_seconds{tenant=%s}" % name)
        assert h is not None and h["count"] == specs[name]["comm_round"]


def test_driver_rejects_job_that_never_fits_and_runs_the_rest():
    jobs = [TenantJob("ok", _job_cfg(seed=0, clients=2, rounds=1)),
            TenantJob("whale", _job_cfg(seed=1, clients=2, rounds=1))]
    driver = MultiTenantSimDriver(jobs, capacity_bytes=10_000)
    # 10kB fits the tiny lr model but not... nothing actually: pick capacity
    # from the first job's real envelope so exactly one fits
    sim, _apply, env = driver._build(jobs[0])
    driver = MultiTenantSimDriver(jobs, capacity_bytes=env.device_memory_bytes)
    results = driver.run()
    assert results["ok"].ok
    assert results["whale"].verdict.queued or results["whale"].ok
    # queued job was promoted when "ok" released capacity, then ran
    assert results["whale"].ok


# --- chaos isolation drill ---------------------------------------------------


@pytest.mark.chaos
def test_tenant_isolation_server_crash_recovers_from_own_store(tmp_path):
    """Kill tenant A's server mid-run (seeded crash plan). It must resume
    from ITS OWN RoundStateStore namespace and finish, while tenant B's
    deployment — running concurrently under its own telemetry scope — never
    sees a fault. Per-tenant fault counters prove the blast radius."""
    import threading as _th

    from fedml_tpu.comm import LoopbackHub
    from fedml_tpu.cross_silo.chaos import run_chaos_drill
    from fedml_tpu.cross_silo.horizontal_api import FedML_Horizontal

    results = {}

    def healthy_tenant():
        results["b"] = run_chaos_drill(
            tenant="tenant-b", fault_drop_rate=0.0, comm_round=3,
            round_ckpt_path=str(tmp_path / "tenant-b" / "round_state.msgpack"),
        )

    tb = _th.Thread(target=healthy_tenant, daemon=True)
    tb.start()

    # tenant A: crash its server right after round 0 checkpoints
    cfg = dict(
        dataset="mnist", model="lr", debug_small_data=True,
        client_num_in_total=2, client_num_per_round=2, comm_round=3,
        learning_rate=0.1, epochs=1, batch_size=8, frequency_of_the_test=1,
        random_seed=0,
        round_ckpt_path=str(tmp_path / "tenant-a" / "round_state.msgpack"),
        ckpt_every_rounds=1,
    )
    with telemetry.tenant_scope("tenant-a"):
        args_a = fedml_tpu.init(config={**cfg, "fault_crash_rank": 0,
                                        "fault_crash_at_round": 1})
        hub = LoopbackHub()
        server_a = FedML_Horizontal(args_a, 0, 2, backend="LOOPBACK", hub=hub)
        clients = [FedML_Horizontal(args_a, r, 2, backend="LOOPBACK", hub=hub)
                   for r in (1, 2)]

    def scoped_run(node):
        def runner():
            with telemetry.tenant_scope("tenant-a"):
                node.run()
        return runner

    client_threads = [_th.Thread(target=scoped_run(c), daemon=True)
                      for c in clients]
    for t in client_threads:
        t.start()
    with telemetry.tenant_scope("tenant-a"):
        server_a.start()
    thread_a = _th.Thread(target=scoped_run(server_a), daemon=True)
    thread_a.start()
    thread_a.join(timeout=60)
    assert not thread_a.is_alive()
    assert len(server_a.history) == 1  # died after exactly one round
    assert server_a.com_manager.crashed

    # restart: fresh server, same hub + SAME per-tenant checkpoint namespace
    stale = hub.register(0)
    while not stale.empty():
        stale.get_nowait()
    with telemetry.tenant_scope("tenant-a"):
        args_b = fedml_tpu.init(config=cfg)
        server_a2 = FedML_Horizontal(args_b, 0, 2, backend="LOOPBACK",
                                     hub=hub)
    assert server_a2.round_idx == 1  # resumed from its own store
    thread_a2 = _th.Thread(target=scoped_run(server_a2), daemon=True)
    thread_a2.start()
    with telemetry.tenant_scope("tenant-a"):
        server_a2.start()
    thread_a2.join(timeout=90)
    assert not thread_a2.is_alive()
    assert [h["round"] for h in server_a2.history] == [1, 2]

    tb.join(timeout=120)
    assert not tb.is_alive()
    # tenant B finished every round, fault-free, while A was crashing
    assert results["b"].ok
    assert results["b"].rounds_completed == 3
    assert results["b"].faults_injected in ({}, {"total": 0.0})

    # blast radius in the registry: crash faults are A's, and A's only
    cs = _counters()
    a_faults = sum(v for k, v in cs.items()
                   if k.startswith("fedml_faults_injected_total{")
                   and "tenant=tenant-a" in k)
    b_faults = sum(v for k, v in cs.items()
                   if k.startswith("fedml_faults_injected_total{")
                   and "tenant=tenant-b" in k)
    assert a_faults >= 1
    assert b_faults == 0

    for t in client_threads:
        t.join(timeout=10)
        assert not t.is_alive()


# --- loadgen -----------------------------------------------------------------


@pytest.mark.loadgen
def test_loadgen_sustains_10k_checkins_per_sec_with_bounded_queue():
    from fedml_tpu.cross_silo.loadgen import run_loadgen

    report = run_loadgen(duration_s=1.0, producers=2, queue_maxsize=256,
                         tenants=2, churn=0.1, seed=0)
    assert report.ok, report.summary()
    # the acceptance floor; smoke runs on this CPU tier sit around 50k/s
    assert report.offered_rate >= 10_000.0
    assert report.max_queue_depth <= 256
    # shedding happened (unthrottled producers vs one codec-bound consumer)
    # and is visible per tenant in the registry deltas the report carries
    assert report.shed > 0
    assert sum(report.per_tenant_shed.values()) == pytest.approx(
        report.shed)
    assert set(report.per_tenant_shed) == {"tenant0", "tenant1"}
    rec = report.json_record()
    assert rec["ok"] and rec["queue_depth_bounded"]


@pytest.mark.loadgen
def test_loadgen_churn_is_seed_deterministic():
    from fedml_tpu.cross_silo.loadgen import run_loadgen

    a = run_loadgen(duration_s=0.2, producers=1, target_rate=5_000.0,
                    tenants=2, churn=0.3, seed=42, population=500)
    b = run_loadgen(duration_s=0.2, producers=1, target_rate=5_000.0,
                    tenants=2, churn=0.3, seed=42, population=500)
    # same seed, same device sequence -> same churn fraction (the counts
    # differ only by how many check-ins fit in the wall-clock window)
    assert a.churned / max(a.offered + a.churned, 1) == pytest.approx(
        b.churned / max(b.offered + b.churned, 1), abs=0.02)
