"""Attack harness vs robust aggregation: the stubbed reference attacker made
functional (core/security.py) and evaluated against the defenses."""

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.core.robust import coordinate_median, norm_clip_stacked, trimmed_mean
from fedml_tpu.core.security import (
    FedMLAttacker,
    gaussian_attack,
    label_flip_data,
    scale_attack,
    sign_flip_attack,
)


def _honest_updates(C=10, d=32, seed=0):
    rng = np.random.default_rng(seed)
    base = rng.normal(size=d).astype(np.float32)
    # honest clients: small perturbations of a common direction
    return {"w": jnp.asarray(base[None] + 0.05 * rng.normal(size=(C, d)).astype(np.float32))}


def _mean(stacked):
    return jax.tree.map(lambda u: u.mean(axis=0), stacked)


def test_scale_attack_breaks_mean_median_survives():
    ups = _honest_updates()
    honest_mean = _mean(ups)["w"]
    mask = jnp.asarray(np.eye(10, dtype=np.float32)[0])  # client 0 attacks
    attacked = scale_attack(ups, mask, boost=50.0)

    naive = _mean(attacked)["w"]
    med = coordinate_median(attacked)["w"]
    err_naive = float(jnp.linalg.norm(naive - honest_mean))
    err_median = float(jnp.linalg.norm(med - honest_mean))
    assert err_naive > 5 * err_median
    assert err_median < 0.5


def test_sign_flip_attack_trimmed_mean_survives():
    ups = _honest_updates()
    honest_mean = _mean(ups)["w"]
    mask = jnp.asarray((np.arange(10) < 2).astype(np.float32))  # 2 attackers
    attacked = sign_flip_attack(ups, mask, strength=20.0)

    naive = _mean(attacked)["w"]
    trimmed = trimmed_mean(attacked, trim_ratio=0.2)["w"]
    assert float(jnp.linalg.norm(naive - honest_mean)) > \
        3 * float(jnp.linalg.norm(trimmed - honest_mean))


def test_gaussian_attack_norm_clip_bounds_damage():
    ups = _honest_updates()
    mask = jnp.asarray(np.eye(10, dtype=np.float32)[3])
    attacked = gaussian_attack(ups, mask, jax.random.PRNGKey(0), std=100.0)
    clipped = norm_clip_stacked(attacked, norm_bound=8.0)  # honest norms ~5.7
    # after clipping, no client's update norm exceeds the bound
    norms = jnp.sqrt((clipped["w"] ** 2).sum(axis=1))
    assert float(norms.max()) <= 8.0 + 1e-3
    # honest clients below the bound are untouched
    np.testing.assert_allclose(
        np.asarray(clipped["w"][1]), np.asarray(attacked["w"][1]), atol=1e-6)


def test_attacker_facade_and_label_flip():
    atk = FedMLAttacker(attack_type="scale", attacker_ratio=0.3, boost=7.0, seed=1)
    mask = atk.attacker_mask(10)
    assert mask.sum() == 3
    ups = _honest_updates()
    out = atk.attack(ups, 10)["w"]
    ratio = np.asarray(jnp.linalg.norm(out, axis=1) /
                       jnp.linalg.norm(ups["w"], axis=1))
    assert np.allclose(np.sort(ratio)[-3:], 7.0, atol=1e-4)

    y = np.array([0, 1, 9])
    np.testing.assert_array_equal(label_flip_data(y, 10), [9, 8, 0])


def test_simulator_injected_attack_defense_end_to_end():
    """args.attack_type wires the attacker into aggregation: under a scale
    attack, median-defended FedAvg_robust clearly beats plain FedAvg."""
    import fedml_tpu
    from fedml_tpu.simulation import build_simulator

    def run(optimizer, defense=None):
        cfg = dict(
            dataset="digits", model="lr", partition_method="homo",
            client_num_in_total=10, client_num_per_round=10, comm_round=12,
            learning_rate=0.3, epochs=1, batch_size=32,
            frequency_of_the_test=11, random_seed=0,
            attack_type="scale", attacker_ratio=0.2, attack_boost=50.0,
            federated_optimizer=optimizer,
        )
        if defense:
            cfg["defense_type"] = defense
        args = fedml_tpu.init(config=cfg)
        sim, apply_fn = build_simulator(args)
        return sim.run(apply_fn, log_fn=None)[-1]["test_acc"]

    acc_plain = run("FedAvg")
    acc_robust = run("FedAvg_robust", defense="coordinate_median")
    assert acc_robust > 0.7, acc_robust
    assert acc_robust > acc_plain + 0.1, (acc_plain, acc_robust)
