"""Tiered federation (tentpole): the single-process reference driver vs the
real multi-process tier plane — bit-identity over loopback AND grpc, exact
phase accounting, leaf-crash failover with shard rehydration, partition
healing with re-adoption, and fixed logical shards under elastic membership.
"""

import numpy as np
import pytest

import fedml_tpu
from fedml_tpu.core import telemetry
from fedml_tpu.cross_silo.chaos import TIER_DEFAULTS
from fedml_tpu.simulation.federation import (
    TierConfig,
    build_tiered_simulator,
    round_chunks,
    run_tiered_federation,
)


@pytest.fixture(autouse=True)
def _fresh_telemetry():
    telemetry.configure(enabled=True, reset=True)
    yield
    telemetry.configure(enabled=True, reset=True)


def _cfg(**overrides):
    cfg = dict(TIER_DEFAULTS)
    cfg.update(overrides)
    return cfg


def _leaves(params):
    import jax

    return [np.asarray(x) for x in jax.tree_util.tree_leaves(params)]


def _assert_params_equal(a, b):
    la, lb = _leaves(a), _leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(x, y)


def _reference(cfg):
    sim, apply_fn = build_tiered_simulator(fedml_tpu.init(config=cfg))
    hist = sim.run(apply_fn, log_fn=None)
    return sim, hist


def _train_metrics(history):
    return [(r["round"], r["train_loss"], r["train_acc"]) for r in history]


# --- bit-identity: reference vs the wire --------------------------------------


def test_single_process_reference_is_repeatable():
    cfg = _cfg(comm_round=2)
    sim1, hist1 = _reference(cfg)
    sim2, hist2 = _reference(cfg)
    _assert_params_equal(sim1.params, sim2.params)
    assert _train_metrics(hist1) == _train_metrics(hist2)


def test_loopback_tier_bit_identical_to_reference():
    cfg = _cfg(comm_round=3)
    ref_sim, ref_hist = _reference(cfg)
    root = run_tiered_federation(fedml_tpu.init(config=cfg))
    assert len(root.history) == cfg["comm_round"]
    _assert_params_equal(root.sim.params, ref_sim.params)
    assert _train_metrics(root.history) == _train_metrics(ref_hist)
    # exactly-once over the wire: every cohort member committed, no dups
    ledger = root.state.ledger
    assert int(ledger.total_commits) == (cfg["comm_round"]
                                         * cfg["client_num_per_round"])
    assert int(ledger.duplicates) == 0
    assert root.failovers == 0 and root.rehydrations == 0


def test_grpc_tier_bit_identical_to_reference():
    cfg = _cfg(comm_round=2, grpc_base_port=27890)
    ref_sim, ref_hist = _reference(cfg)
    root = run_tiered_federation(fedml_tpu.init(config=cfg), backend="GRPC")
    _assert_params_equal(root.sim.params, ref_sim.params)
    assert _train_metrics(root.history) == _train_metrics(ref_hist)
    assert int(root.state.ledger.duplicates) == 0


# --- phase accounting ---------------------------------------------------------


def test_reference_phase_sums_are_exact():
    _, hist = _reference(_cfg(comm_round=2))
    for rec in hist:
        phases = rec["phases"]
        assert {"device", "fold", "checkpoint", "host_other"} <= set(phases)
        assert abs(sum(phases.values()) - rec["round_time"]) < 1e-9


def test_root_phase_sums_are_exact():
    root = run_tiered_federation(fedml_tpu.init(config=_cfg(comm_round=2)))
    for rec in root.history:
        phases = rec["phases"]
        assert {"dispatch", "leaf_wait", "fold",
                "checkpoint", "host_other"} <= set(phases)
        assert abs(sum(phases.values()) - rec["round_time"]) < 1e-9
        # the wait for leaf partials dominates a wire round; it must be
        # attributed, not lumped into host_other
        assert phases["leaf_wait"] >= 0.0


# --- failure story ------------------------------------------------------------


def test_leaf_crash_failover_rehydrates_and_stays_bit_identical(tmp_path):
    cfg = _cfg(comm_round=3)
    ref_sim, ref_hist = _reference(cfg)
    faulted = _cfg(comm_round=3, hier_shard_dir=str(tmp_path),
                   fault_leaf_crash_rank=1, fault_leaf_crash_at_round=1)
    root = run_tiered_federation(fedml_tpu.init(config=faulted))
    # the leaf dies on the SEND path — its partial exists on disk and the
    # root recovers it from the shard store instead of recomputing
    assert root.failovers >= 1
    assert root.rehydrations >= 1
    ledger = root.state.ledger
    assert int(ledger.duplicates) == 0
    assert int(ledger.total_commits) == (cfg["comm_round"]
                                         * cfg["client_num_per_round"])
    _assert_params_equal(root.sim.params, ref_sim.params)
    assert _train_metrics(root.history) == _train_metrics(ref_hist)


def test_partition_heals_and_leaf_is_readopted():
    cfg = _cfg(comm_round=4)
    ref_sim, ref_hist = _reference(cfg)
    # cut root<->leaf1 for round 1 only; leaf 2 is made deterministically
    # slow so rounds outlast the heartbeat interval — the healed leaf's
    # heartbeats need wall-clock room to land before the run ends
    faulted = _cfg(comm_round=4,
                   fault_partition_ranks_a=[0], fault_partition_ranks_b=[1],
                   fault_partition_rounds=(1, 2),
                   fault_slow_leaf_ranks=[2], fault_slow_leaf_delay_s=0.3)
    root = run_tiered_federation(fedml_tpu.init(config=faulted))
    assert root.failovers >= 1  # the cut round was recovered by the root
    counters = telemetry.get_registry().snapshot()["counters"]
    assert counters.get("fedml_faults_injected_total{action=partition}", 0) > 0
    # elastic membership, both directions: leaf 1 was expelled during the
    # window and re-adopted (heartbeat-as-rejoin) after it closed
    assert counters.get("fedml_faults_injected_total{action=leaf_join}",
                        0) >= 1
    with root._membership_lock:
        assert root._live == {1, 2}
    # and none of it moved the math
    ledger = root.state.ledger
    assert int(ledger.duplicates) == 0
    _assert_params_equal(root.sim.params, ref_sim.params)
    assert _train_metrics(root.history) == _train_metrics(ref_hist)


def test_rejoin_across_version_log_trim_resyncs_exactly_once():
    """Elastic membership x version-log retention: with only the latest
    version retained (``round_store_keep_versions=1``), a leaf expelled
    during a partition window rejoins AFTER its last-synced version has
    fallen off the retained log. Re-adoption must resync it from the live
    model (full sync, not log replay) with no duplicate and no lost
    commits — the exactly-once invariant survives the trim boundary."""
    cfg = _cfg(comm_round=4, round_store_keep_versions=1)
    ref_sim, ref_hist = _reference(cfg)
    faulted = _cfg(comm_round=4, round_store_keep_versions=1,
                   fault_partition_ranks_a=[0], fault_partition_ranks_b=[1],
                   fault_partition_rounds=(1, 2),
                   fault_slow_leaf_ranks=[2], fault_slow_leaf_delay_s=0.3)
    root = run_tiered_federation(fedml_tpu.init(config=faulted))
    # the window was recovered and the leaf re-adopted
    assert root.failovers >= 1
    counters = telemetry.get_registry().snapshot()["counters"]
    assert counters.get("fedml_faults_injected_total{action=leaf_join}",
                        0) >= 1
    with root._membership_lock:
        assert root._live == {1, 2}
    # the trim actually bit: only one retained entry, and its version is
    # past anything leaf 1 saw before the cut (expelled during round 1,
    # so it last synced version <= 1) — the rejoin crossed the boundary
    state = root.state
    assert len(state.version_log) == 1
    assert state.version_log[0][0] == state.model_version
    assert state.version_log[0][0] > 1
    # exactly-once across the trim: no double-folds, no lost commits
    assert int(state.ledger.duplicates) == 0
    assert int(state.ledger.total_commits) == (cfg["comm_round"]
                                               * cfg["client_num_per_round"])
    # and the resynced membership history is bit-identical to reference
    _assert_params_equal(root.sim.params, ref_sim.params)
    assert _train_metrics(root.history) == _train_metrics(ref_hist)


# --- fixed logical shards -----------------------------------------------------


def test_round_chunks_are_membership_independent():
    """The cohort is always split into ``num_leaves`` chunks at the same
    offsets — membership elasticity changes which process computes a chunk,
    never the chunk boundaries. That invariant is what makes every
    membership history bit-identical to the reference."""
    sim, _ = build_tiered_simulator(
        fedml_tpu.init(config=_cfg(comm_round=1)))
    cfg, tier = sim.cfg, sim.tier
    ids_a, chunks_a = round_chunks(cfg, tier, 0)
    ids_b, chunks_b = round_chunks(cfg, tier, 0)
    assert list(ids_a) == list(ids_b)
    assert chunks_a == chunks_b
    assert len(chunks_a) == tier.num_leaves
    # chunks tile the cohort contiguously, no gaps or overlaps
    flat = [c for chunk in chunks_a for c in chunk["client_ids"]]
    assert flat == [int(c) for c in ids_a]
    assert [c["lo"] for c in chunks_a] == [
        sum(len(chunks_a[j]["client_ids"]) for j in range(i))
        for i in range(len(chunks_a))]
    # a different round resamples the cohort
    ids_c, _ = round_chunks(cfg, tier, 1)
    assert list(ids_c) != list(ids_a)


def test_tier_config_from_args_reads_hier_keys():
    args = fedml_tpu.init(config=_cfg(
        comm_round=1, hier_num_leaves=3, lease_ttl_s=2.5,
        lease_heartbeat_s=0.7, hier_staleness_alpha=0.25,
        round_store_keep_versions=4))
    tier = TierConfig.from_args(args)
    assert tier.num_leaves == 3
    assert tier.lease_ttl_s == 2.5
    assert tier.heartbeat_s == 0.7
    assert tier.staleness_alpha == 0.25
    assert tier.keep_versions == 4
