"""Real-wire MQTT 3.1.1 (client + broker over TCP sockets) and the S3 driver.

VERDICT r2 missing #1: the reference's production backend speaks actual MQTT
(``mqtt_s3_multi_clients_comm_manager.py:18``) and real S3
(``remote_storage.py:39``). These tests exercise actual MQTT 3.1.1 frames
over localhost sockets — including a raw-socket peer that speaks literal
protocol bytes, proving wire compatibility rather than just API symmetry —
and the boto3-surface S3 driver against a stub client.
"""

import socket
import struct
import threading
import time

import numpy as np
import pytest

from fedml_tpu.comm import Message, MqttS3CommManager
from fedml_tpu.comm.mqtt_wire import (
    MqttBroker,
    MqttClient,
    MqttWireBroker,
    topic_matches,
)
from fedml_tpu.comm.store import InMemoryBlobStore, S3BlobStore


def _wait(pred, timeout=5.0):
    deadline = time.time() + timeout
    while not pred() and time.time() < deadline:
        time.sleep(0.01)
    assert pred()


def test_topic_filter_matching():
    assert topic_matches("a/b/c", "a/b/c")
    assert not topic_matches("a/b/c", "a/b")
    assert topic_matches("a/+/c", "a/x/c")
    assert not topic_matches("a/+/c", "a/x/y/c")
    assert topic_matches("a/#", "a/x/y/c")
    assert topic_matches("#", "anything/at/all")
    assert not topic_matches("a/#/b", "a/x/b")  # '#' must be last
    assert not topic_matches("a/+", "a")


def test_mqtt_pubsub_roundtrip_qos0_and_qos1():
    broker = MqttBroker()
    try:
        sub = MqttClient(broker.host, broker.port, keepalive=2)
        pub = MqttClient(broker.host, broker.port, keepalive=2)
        got = []
        sub.subscribe("fedml/run1/+", lambda t, p: got.append((t, p)))
        pub.publish("fedml/run1/7", b"qos0-payload", qos=0)
        pub.publish("fedml/run1/8", b"qos1-payload", qos=1)  # blocks on PUBACK
        _wait(lambda: len(got) == 2)
        assert dict(got) == {"fedml/run1/7": b"qos0-payload",
                             "fedml/run1/8": b"qos1-payload"}
        # keepalive: outlive one ping interval, connection stays up
        time.sleep(1.2)
        pub.publish("fedml/run1/7", b"after-ping", qos=1)
        _wait(lambda: len(got) == 3)
        sub.disconnect(), pub.disconnect()
    finally:
        broker.close()


def test_mqtt_retained_and_unsubscribe():
    broker = MqttBroker()
    try:
        pub = MqttClient(broker.host, broker.port)
        pub.publish("cfg/topology", b"ring", retain=True, qos=1)
        late = MqttClient(broker.host, broker.port)
        got = []
        late.subscribe("cfg/#", lambda t, p: got.append((t, p)))
        _wait(lambda: got == [("cfg/topology", b"ring")])  # retained delivery
        late.unsubscribe("cfg/#")
        pub.publish("cfg/topology", b"star", qos=1)
        time.sleep(0.2)
        assert len(got) == 1  # unsubscribed: no new delivery
        pub.disconnect(), late.disconnect()
    finally:
        broker.close()


def test_raw_socket_peer_speaks_literal_mqtt_bytes():
    """A hand-rolled socket exchanges literal MQTT 3.1.1 frames with the
    broker — the wire-compatibility proof (any conformant client would
    produce/accept exactly these bytes)."""
    broker = MqttBroker()
    try:
        s = socket.create_connection((broker.host, broker.port), timeout=5)
        # CONNECT: MQTT level 4, clean session, keepalive 60, client id "raw"
        vh = b"\x00\x04MQTT\x04\x02\x00\x3c" + b"\x00\x03raw"
        s.sendall(bytes([0x10, len(vh)]) + vh)
        assert s.recv(4) == b"\x20\x02\x00\x00"  # CONNACK, rc=0
        # SUBSCRIBE pid=1 to "t/raw" qos1 (flags nibble must be 0b0010)
        body = b"\x00\x01" + b"\x00\x05t/raw" + b"\x01"
        s.sendall(bytes([0x82, len(body)]) + body)
        assert s.recv(5) == b"\x90\x03\x00\x01\x01"  # SUBACK granted qos1
        # a framework client publishes; the raw peer reads the PUBLISH frame
        c = MqttClient(broker.host, broker.port)
        c.publish("t/raw", b"hello", qos=0)
        frame = s.recv(64)
        # broker routes qos0 publishes as qos0: fixed header 0x30
        assert frame[0] == 0x30
        assert frame[1] == len(frame) - 2
        tlen = struct.unpack(">H", frame[2:4])[0]
        assert frame[4:4 + tlen] == b"t/raw"
        assert frame[4 + tlen:] == b"hello"
        # PINGREQ -> PINGRESP, literal bytes
        s.sendall(b"\xc0\x00")
        assert s.recv(2) == b"\xd0\x00"
        # raw peer publishes qos1; broker must PUBACK then deliver
        got = []
        c.subscribe("t/back", lambda t, p: got.append(p))
        pb = b"\x00\x06t/back" + b"\x00\x09" + b"frombytes"
        s.sendall(bytes([0x32, len(pb)]) + pb)
        assert s.recv(4) == b"\x40\x02\x00\x09"  # PUBACK pid=9
        _wait(lambda: got == [b"frombytes"])
        s.sendall(b"\xe0\x00")  # DISCONNECT
        s.close()
        c.disconnect()
    finally:
        broker.close()


def test_callback_may_publish_qos1_on_same_client():
    """Review regression: callbacks run off the reader thread, so a
    subscriber replying with publish(qos=1) must not deadlock on its own
    PUBACK."""
    broker = MqttBroker()
    try:
        c = MqttClient(broker.host, broker.port)
        got = []

        def reply(topic, payload):
            c.publish("pong", payload + b"!", qos=1)  # needs reader alive

        c.subscribe("ping", reply)
        c.subscribe("pong", lambda t, p: got.append(p))
        t0 = time.time()
        c.publish("ping", b"hi", qos=1)
        _wait(lambda: got == [b"hi!"])
        assert time.time() - t0 < 5  # no 10s ack starvation
        c.disconnect()
    finally:
        broker.close()


def test_raw_qos2_publish_exactly_once_handshake():
    """A conformant client publishing QoS2 gets PUBREC/PUBCOMP and the
    message routes exactly once, on PUBREL."""
    broker = MqttBroker()
    try:
        c = MqttClient(broker.host, broker.port)
        got = []
        c.subscribe("q2", lambda t, p: got.append(p))
        s = socket.create_connection((broker.host, broker.port), timeout=5)
        vh = b"\x00\x04MQTT\x04\x02\x00\x3c" + b"\x00\x02r2"
        s.sendall(bytes([0x10, len(vh)]) + vh)
        assert s.recv(4) == b"\x20\x02\x00\x00"
        body = b"\x00\x02q2" + b"\x00\x05" + b"once"  # PUBLISH qos2 pid=5
        s.sendall(bytes([0x34, len(body)]) + body)
        assert s.recv(4) == b"\x50\x02\x00\x05"  # PUBREC
        time.sleep(0.2)
        assert got == []  # not routed before PUBREL
        s.sendall(b"\x62\x02\x00\x05")  # PUBREL (flags 0b0010)
        assert s.recv(4) == b"\x70\x02\x00\x05"  # PUBCOMP
        _wait(lambda: got == [b"once"])
        s.close()
        c.disconnect()
    finally:
        broker.close()


def test_qos_downgrade_to_granted():
    """A QoS0 subscription must receive QoS1 publishes as QoS0 frames."""
    broker = MqttBroker()
    try:
        s = socket.create_connection((broker.host, broker.port), timeout=5)
        vh = b"\x00\x04MQTT\x04\x02\x00\x3c" + b"\x00\x02dg"
        s.sendall(bytes([0x10, len(vh)]) + vh)
        assert s.recv(4) == b"\x20\x02\x00\x00"
        body = b"\x00\x01" + b"\x00\x03t/d" + b"\x00"  # subscribe qos0
        s.sendall(bytes([0x82, len(body)]) + body)
        assert s.recv(5) == b"\x90\x03\x00\x01\x00"
        c = MqttClient(broker.host, broker.port)
        c.publish("t/d", b"x", qos=1)
        frame = s.recv(32)
        assert frame[0] == 0x30  # QoS0 fixed header — no packet id appended
        assert frame[-1:] == b"x" and len(frame) == 2 + 2 + 3 + 1
        s.close()
        c.disconnect()
    finally:
        broker.close()


def test_mqtt_s3_backend_over_real_wire():
    """The MQTT+S3 comm manager running its control plane over actual MQTT
    TCP connections (one per rank, like the reference's paho clients)."""
    broker = MqttBroker()
    store = InMemoryBlobStore()
    try:
        server_conn = MqttWireBroker(broker.host, broker.port, client_id="srv")
        client_conn = MqttWireBroker(broker.host, broker.port, client_id="cl1")
        server = MqttS3CommManager(server_conn, store, rank=0, size=2,
                                   run_id="wire9", owns_broker=True)
        received = []

        class Obs:
            def receive_message(self, t, msg):
                received.append(msg)
                server.stop_receive_message()

        server.add_observer(Obs())
        client = MqttS3CommManager(client_conn, store, rank=1, size=2,
                                   run_id="wire9", owns_broker=True)
        big = {"w": np.arange(50_000, dtype=np.float32)}
        msg = Message(type=3, sender_id=1, receiver_id=0)
        msg.add_params(Message.MSG_ARG_KEY_MODEL_PARAMS, big)
        client.send_message(msg)
        t = threading.Thread(target=server.handle_receive_message, daemon=True)
        t.start()
        t.join(timeout=10)
        assert received
        got = received[0].get(Message.MSG_ARG_KEY_MODEL_PARAMS)
        np.testing.assert_array_equal(got["w"], big["w"])
        assert store.list_keys()  # the big payload rode the blob store
        client.stop_receive_message()
    finally:
        broker.close()


# --- S3 driver against a boto3-surface stub --------------------------------

class _StubS3Client:
    """Implements the subset of the boto3 S3 client surface S3BlobStore
    uses, with list pagination, over a dict."""

    def __init__(self, page_size=2):
        self.objects = {}
        self.page_size = page_size

    def put_object(self, Bucket, Key, Body):
        self.objects[(Bucket, Key)] = bytes(Body)

    def get_object(self, Bucket, Key):
        import io

        return {"Body": io.BytesIO(self.objects[(Bucket, Key)])}

    def delete_object(self, Bucket, Key):
        self.objects.pop((Bucket, Key), None)

    def list_objects_v2(self, Bucket, Prefix="", ContinuationToken=None):
        keys = sorted(k for b, k in self.objects
                      if b == Bucket and k.startswith(Prefix))
        start = int(ContinuationToken or 0)
        page = keys[start:start + self.page_size]
        truncated = start + self.page_size < len(keys)
        resp = {"Contents": [{"Key": k} for k in page],
                "IsTruncated": truncated}
        if truncated:
            resp["NextContinuationToken"] = str(start + self.page_size)
        return resp


def test_s3_blob_store_against_stub():
    stub = _StubS3Client(page_size=2)
    store = S3BlobStore("models", prefix="run42", client=stub)
    url = store.put("round0/agg", b"\x01\x02weights")
    assert url == "s3://models/run42/round0/agg"
    assert store.get("round0/agg") == b"\x01\x02weights"
    for i in range(5):  # force pagination in list_keys
        store.put(f"round1/c{i}", bytes([i]))
    assert store.list_keys("round1/") == [f"round1/c{i}" for i in range(5)]
    store.delete("round0/agg")
    with pytest.raises(KeyError):
        store.get("round0/agg")


def test_s3_blob_store_missing_boto3_is_clear():
    import builtins

    real_import = builtins.__import__

    def no_boto3(name, *a, **k):
        if name == "boto3":
            raise ImportError("No module named 'boto3'")
        return real_import(name, *a, **k)

    builtins.__import__ = no_boto3
    try:
        with pytest.raises(RuntimeError, match="boto3"):
            S3BlobStore("bucket")
    finally:
        builtins.__import__ = real_import


def test_mqtt_s3_rides_blob_store_with_wire_broker_inline_small():
    """Small control-only messages stay inline (no store round trip)."""
    broker = MqttBroker()
    store = InMemoryBlobStore()
    try:
        a = MqttWireBroker(broker.host, broker.port)
        b = MqttWireBroker(broker.host, broker.port)
        server = MqttS3CommManager(a, store, rank=0, size=2, run_id="inl",
                                   owns_broker=True)
        got = []

        class Obs:
            def receive_message(self, t, msg):
                got.append(msg)
                server.stop_receive_message()

        server.add_observer(Obs())
        client = MqttS3CommManager(b, store, rank=1, size=2, run_id="inl",
                                   owns_broker=True)
        msg = Message(type=1, sender_id=1, receiver_id=0)
        msg.add_params("status", "ONLINE")
        client.send_message(msg)
        t = threading.Thread(target=server.handle_receive_message, daemon=True)
        t.start()
        t.join(timeout=10)
        assert got and got[0].get("status") == "ONLINE"
        assert store.list_keys() == []  # inline: store untouched
        client.stop_receive_message()
    finally:
        broker.close()


def test_backend_factory_selects_s3_driver_from_config(tmp_path):
    """A configured bucket routes the blob plane to the S3 driver (the
    import shim makes the boto3-absent branch deterministic regardless of
    the environment), and an explicit store_dir kwarg still wins over the
    config bucket (user-proximate precedence)."""
    import builtins
    import json

    import fedml_tpu
    from fedml_tpu.comm.managers import create_comm_backend
    from fedml_tpu.comm.store import FileSystemBlobStore

    cfg = tmp_path / "cfg.json"
    cfg.write_text(json.dumps({
        "mqtt_config": {"broker_dir": str(tmp_path / "broker")},
        "s3_config": {"BUCKET_NAME": "models-bucket"},
    }))
    args = fedml_tpu.init(config=dict(
        dataset="mnist", model="lr", mlops_config_path=str(cfg)))

    real_import = builtins.__import__

    def no_boto3(name, *a, **k):
        if name == "boto3":
            raise ImportError("No module named 'boto3'")
        return real_import(name, *a, **k)

    builtins.__import__ = no_boto3
    try:
        with pytest.raises(RuntimeError, match="boto3"):
            create_comm_backend("MQTT_S3", rank=0, size=2, args=args)
    finally:
        builtins.__import__ = real_import

    # explicit kwarg beats the config bucket — no S3 attempt at all
    mgr = create_comm_backend("MQTT_S3", rank=0, size=2, args=args,
                              store_dir=str(tmp_path / "explicit"))
    try:
        assert isinstance(mgr.store, FileSystemBlobStore)
        assert mgr.store.root == str(tmp_path / "explicit")
    finally:
        mgr.stop_receive_message()
