"""Concurrency stress: interleaved sends on shared transports stay framed.

The reference has no race detection at all (SURVEY.md §5.2 — ad-hoc locks,
threads killed via PyThreadState_SetAsyncExc). These tests hammer the
in-repo transports from many threads and assert zero loss/corruption —
the closest Python gets to a sanitizer pass for the comm plane.
"""

import threading

import numpy as np

from fedml_tpu.comm.message import Message
from fedml_tpu.comm.trpc_backend import TRPCCommManager


def test_trpc_concurrent_senders_no_interleave():
    """8 threads x 25 tensor messages over ONE pipe: every frame must
    arrive intact (the per-receiver send lock is what's under test)."""
    m0 = TRPCCommManager(rank=0, size=2, base_port=24890)
    m1 = TRPCCommManager(rank=1, size=2, base_port=24890)
    n_threads, n_msgs = 8, 25
    try:
        def sender(tid):
            for k in range(n_msgs):
                msg = Message(type="t", sender_id=0, receiver_id=1)
                val = tid * 1000 + k
                msg.add_params("tag", val)
                msg.add_params("tensor", np.full((500,), val, np.float32))
                m0.send_message(msg)

        threads = [threading.Thread(target=sender, args=(t,))
                   for t in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        got = {}
        for _ in range(n_threads * n_msgs):
            msg = m1._inbox.get(timeout=30)
            tag = msg.get("tag")
            arr = msg.get("tensor")
            np.testing.assert_array_equal(arr, np.full((500,), tag, np.float32))
            got[tag] = got.get(tag, 0) + 1
        assert len(got) == n_threads * n_msgs
        assert all(v == 1 for v in got.values())
    finally:
        m0.stop_receive_message()
        m1.stop_receive_message()


def test_pubsub_concurrent_publishers_no_loss():
    """Filesystem broker: concurrent publishers on one topic — atomic
    publishes, no dropped or duplicated deliveries."""
    import tempfile

    from fedml_tpu.comm.pubsub import FileSystemBroker

    with tempfile.TemporaryDirectory() as root:
        broker = FileSystemBroker(root=root)
        seen = []
        lock = threading.Lock()
        broker.subscribe("jobs", lambda topic, payload: (
            lock.__enter__(), seen.append(bytes(payload)), lock.__exit__(None, None, None)))

        def pub(tid):
            for k in range(20):
                broker.publish("jobs", f"{tid}:{k}".encode())

        threads = [threading.Thread(target=pub, args=(t,)) for t in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        deadline = threading.Event()
        for _ in range(200):
            if len(seen) >= 120:
                break
            deadline.wait(0.05)
        assert sorted(seen) == sorted(
            f"{t}:{k}".encode() for t in range(6) for k in range(20))
        broker.close()
