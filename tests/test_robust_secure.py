"""Robust aggregation defenses + LCC secure aggregation + scheduler."""

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.core.robust import (
    RobustAggregator,
    coordinate_median,
    global_norm,
    krum_aggregate,
    krum_scores,
    norm_clip_update,
    pairwise_sq_dists,
    sanitize_stacked,
    trimmed_mean,
)
from fedml_tpu.core.scheduler import balanced_client_schedule, dp_schedule, even_client_schedule
from fedml_tpu.core.secure_agg import (
    DEFAULT_PRIME,
    LightSecAggConfig,
    dequantize_tree,
    lagrange_coeffs,
    lcc_decode,
    lcc_encode,
    modular_inv,
    quantize_tree,
    secure_aggregate,
)


def _stack(trees):
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *trees)


def test_norm_clip_bounds_update_norm():
    update = {"w": jnp.full((10,), 3.0), "b": jnp.ones(())}
    clipped = norm_clip_update(update, norm_bound=1.0)
    assert float(global_norm(clipped)) <= 1.0 + 1e-5
    # direction preserved
    ratio = clipped["w"][0] / clipped["b"]
    assert np.isclose(float(ratio), 3.0, rtol=1e-5)


def test_norm_clip_passthrough_below_bound():
    update = {"w": jnp.full((4,), 0.1)}
    clipped = norm_clip_update(update, norm_bound=10.0)
    np.testing.assert_allclose(np.asarray(clipped["w"]), 0.1, rtol=1e-6)


def test_coordinate_median_rejects_outlier():
    honest = [{"w": jnp.ones(5) * v} for v in (0.9, 1.0, 1.1)]
    byzantine = {"w": jnp.ones(5) * 1e6}
    stacked = _stack(honest + [byzantine])
    agg = coordinate_median(stacked)
    np.testing.assert_allclose(np.asarray(agg["w"]), 1.05, rtol=1e-5)


def test_robust_aggregator_weak_dp_noise_scale():
    ra = RobustAggregator(defense_type="weak_dp", norm_bound=100.0, stddev=0.1)
    stacked = {"w": jnp.ones((8, 1000))}
    agg = ra.aggregate(stacked, jnp.ones(8), rng=jax.random.PRNGKey(0))
    noise = np.asarray(agg["w"]) - 1.0
    assert 0.05 < noise.std() < 0.2


def test_krum_scores_match_numpy_oracle():
    """XLA Krum scores against a direct NumPy transcription of Blanchard et
    al. 2017: score(i) = sum of the C-f-2 smallest ||u_i - u_j||^2, j != i."""
    rng = np.random.default_rng(0)
    updates = rng.normal(size=(7, 13)).astype(np.float32)
    stacked = {"w": jnp.asarray(updates)}
    f = 2
    got = np.asarray(krum_scores(pairwise_sq_dists(stacked), f))
    want = np.empty(7)
    for i in range(7):
        d = np.sort([np.sum((updates[i] - updates[j]) ** 2)
                     for j in range(7) if j != i])
        want[i] = d[: 7 - f - 2].sum()
    np.testing.assert_allclose(got, want, rtol=1e-4)


def test_krum_selects_honest_cluster():
    """Classic Krum picks an update from the tight honest cluster, never a
    far-flung byzantine one; multi-Krum averages exactly the m survivors."""
    honest = [{"w": jnp.ones(6) * (1.0 + 0.01 * i)} for i in range(7)]
    byz = [{"w": jnp.ones(6) * 100.0}, {"w": jnp.ones(6) * -80.0}]
    stacked = _stack(honest + byz)
    w = jnp.ones(9)
    agg, selected = krum_aggregate(stacked, w, n_byz=2, m=1)
    sel = np.nonzero(np.asarray(selected))[0]
    assert len(sel) == 1 and sel[0] < 7, sel
    assert 0.9 < float(np.asarray(agg["w"])[0]) < 1.1
    agg_m, selected_m = krum_aggregate(stacked, w, n_byz=2, m=7)
    sel_m = set(np.nonzero(np.asarray(selected_m))[0].tolist())
    assert sel_m == set(range(7)), sel_m
    np.testing.assert_allclose(
        np.asarray(agg_m["w"]),
        np.mean([1.0 + 0.01 * i for i in range(7)]), rtol=1e-5)


def test_robust_aggregator_krum_family_defends():
    """The three Krum-family defense_types all reject a NaN + scaled pair
    of attackers; krum_fedavg weights survivors by sample count."""
    honest = [{"w": jnp.ones(4) * v} for v in (0.9, 1.0, 1.0, 1.1, 1.05)]
    attackers = [{"w": jnp.full(4, jnp.nan)}, {"w": jnp.ones(4) * 500.0}]
    stacked = _stack(honest + attackers)
    w = jnp.asarray([1.0, 2.0, 2.0, 1.0, 1.0, 5.0, 5.0])
    for defense in ("krum", "multi_krum", "krum_fedavg"):
        ra = RobustAggregator(defense_type=defense, sanitize=True,
                              byzantine_n=2)
        agg, info = ra.aggregate_with_info(stacked, w)
        a = np.asarray(agg["w"])
        assert np.isfinite(a).all(), (defense, a)
        assert 0.85 <= a[0] <= 1.15, (defense, a)
        assert np.asarray(info["quarantine"])[5], defense  # the NaN row
    # sample weighting: survivors 0..4 with weights 1,2,2,1,1
    ra = RobustAggregator(defense_type="krum_fedavg", sanitize=True,
                          byzantine_n=2, multi_krum_m=5)
    agg, info = ra.aggregate_with_info(stacked, w)
    sel = np.asarray(info["selected"])[:5]
    vals = np.array([0.9, 1.0, 1.0, 1.1, 1.05])
    ws = np.array([1.0, 2.0, 2.0, 1.0, 1.0]) * sel
    np.testing.assert_allclose(
        np.asarray(agg["w"])[0], (vals * ws).sum() / ws.sum(), rtol=1e-5)


def test_sanitize_quarantines_nonfinite_and_outliers():
    honest = [{"w": jnp.ones(8) * v} for v in (0.9, 1.0, 1.1, 1.0, 0.95)]
    rows = honest + [{"w": jnp.full(8, jnp.nan)}, {"w": jnp.ones(8) * 1e4}]
    stacked = _stack(rows)
    weights = jnp.ones(7)
    clean, w, quar, z = sanitize_stacked(stacked, weights, z_thresh=6.0)
    q = np.asarray(quar)
    assert q.tolist() == [False] * 5 + [True, True]
    # quarantined rows are ZEROED, not just zero-weighted (0 * nan == nan)
    cw = np.asarray(clean["w"])
    assert np.isfinite(cw).all()
    np.testing.assert_allclose(cw[5], 0.0)
    np.testing.assert_allclose(cw[6], 0.0)
    np.testing.assert_allclose(np.asarray(w), [1] * 5 + [0, 0])
    assert np.isinf(np.asarray(z)[5])  # non-finite rows pin z to +inf


def test_sanitize_uniform_cohort_no_false_positives():
    """Near-identical norms (fp jitter only) must not be flagged — the MAD
    floor is relative to the median."""
    rows = [{"w": jnp.ones(16) * (1.0 + 1e-7 * i)} for i in range(8)]
    _, w, quar, _ = sanitize_stacked(_stack(rows), jnp.ones(8))
    assert not np.asarray(quar).any()
    np.testing.assert_allclose(np.asarray(w), 1.0)


def test_sanitize_valid_mask_matches_subset_run():
    """Padded (invalid) rows must not shift the median/MAD statistics: a
    masked 8-row cohort sanitizes identically to the 6-row subset, and the
    pad rows come back unquarantined with z=0."""
    rows = [{"w": jnp.ones(8) * v} for v in (0.9, 1.0, 1.1, 1.0, 0.95, 1e4)]
    # zero pad rows: perfectly plausible "inliers" that would drag the
    # median/MAD if counted (the failure mode the mask exists to prevent)
    pads = [{"w": jnp.zeros(8)}] * 2
    stacked = _stack(rows + pads)
    valid = jnp.asarray([True] * 6 + [False] * 2)
    weights = jnp.asarray([1.0] * 6 + [0.0] * 2)  # pads pre-zeroed upstream
    clean, w, quar, z = sanitize_stacked(stacked, weights, valid=valid)
    c_s, w_s, quar_s, z_s = sanitize_stacked(_stack(rows), jnp.ones(6))
    np.testing.assert_array_equal(np.asarray(quar)[:6], np.asarray(quar_s))
    np.testing.assert_array_equal(np.asarray(clean["w"])[:6],
                                  np.asarray(c_s["w"]))
    np.testing.assert_array_equal(np.asarray(z)[:6], np.asarray(z_s))
    np.testing.assert_array_equal(np.asarray(w)[:6], np.asarray(w_s))
    # pad rows: never quarantined (the padding weight mask already zeroes
    # them), z pinned to 0 so they can't trip callers' z-based logging
    assert not np.asarray(quar)[6:].any()
    np.testing.assert_array_equal(np.asarray(z)[6:], 0.0)
    np.testing.assert_array_equal(np.asarray(w)[6:], 0.0)


def test_pairwise_dists_tiled_matches_untiled():
    """The client-axis tiling (how the sharded Krum path bounds the C x C
    distance matrix working set) is exact — including a non-divisor tile,
    whose last partial block is zero-padded and trimmed. Only a
    non-positive tile is a hard error."""
    import pytest

    rng = np.random.default_rng(1)
    stacked = {"w": jnp.asarray(rng.normal(size=(8, 5)).astype(np.float32))}
    base = np.asarray(pairwise_sq_dists(stacked))
    for t in (1, 2, 3, 4, 8):
        np.testing.assert_allclose(
            np.asarray(pairwise_sq_dists(stacked, tile_size=t)), base,
            rtol=1e-5)
    with pytest.raises(ValueError, match="must be positive"):
        pairwise_sq_dists(stacked, tile_size=0)


def test_pairwise_dists_valid_mask_isolates_pads():
    rng = np.random.default_rng(2)
    stacked = {"w": jnp.asarray(rng.normal(size=(6, 4)).astype(np.float32))}
    valid = jnp.asarray([True] * 4 + [False] * 2)
    d = np.asarray(pairwise_sq_dists(stacked, valid=valid))
    base = np.array(pairwise_sq_dists({"w": stacked["w"][:4]}))
    # the valid path pins its diagonal to exactly 0; the plain path leaves
    # fp residue there — compare off-diagonal entries
    np.fill_diagonal(base, 0.0)
    np.testing.assert_allclose(d[:4, :4], base, rtol=1e-5)
    # any pair touching a pad row is pushed to +inf (never a Krum
    # neighbour), except the self-distance diagonal which stays 0
    assert np.isinf(d[4:, :4]).all() and np.isinf(d[:4, 4:]).all()
    np.testing.assert_array_equal(np.diag(d), 0.0)


def test_krum_valid_mask_matches_subset_selection():
    """Krum on a padded cohort (valid mask + n_valid-adjusted neighbour
    count) selects the same clients and aggregates to the same value as
    Krum on the unpadded subset."""
    honest = [{"w": jnp.ones(6) * (1.0 + 0.01 * i)} for i in range(7)]
    byz = [{"w": jnp.ones(6) * 100.0}, {"w": jnp.ones(6) * -80.0}]
    stacked9 = _stack(honest + byz)
    agg9, sel9 = krum_aggregate(stacked9, jnp.ones(9), n_byz=2, m=3)
    pads = [{"w": jnp.full(6, 7e7)}] * 3
    stacked12 = _stack(honest + byz + pads)
    valid = jnp.asarray([True] * 9 + [False] * 3)
    agg12, sel12 = krum_aggregate(stacked12, jnp.ones(12), n_byz=2, m=3,
                                  valid=valid, tile_size=4)
    np.testing.assert_array_equal(np.asarray(sel12)[:9], np.asarray(sel9))
    assert not np.asarray(sel12)[9:].any()
    np.testing.assert_allclose(np.asarray(agg12["w"]),
                               np.asarray(agg9["w"]), rtol=1e-5)


def test_weighted_trimmed_mean_matches_oracle():
    x = np.array([[-50.0], [1.0], [2.0], [3.0], [60.0]], np.float32)
    w = np.array([9.0, 1.0, 2.0, 3.0, 9.0], np.float32)
    got = trimmed_mean({"v": jnp.asarray(x)}, trim_ratio=0.2,
                       weights=jnp.asarray(w))
    # k=1: extremes (and their heavy weights) trimmed; weighted mean of rest
    want = (1.0 * 1 + 2.0 * 2 + 3.0 * 3) / (1 + 2 + 3)
    np.testing.assert_allclose(np.asarray(got["v"])[0], want, rtol=1e-6)
    # unweighted path unchanged: plain mean of the surviving slice
    got_u = trimmed_mean({"v": jnp.asarray(x)}, trim_ratio=0.2)
    np.testing.assert_allclose(np.asarray(got_u["v"])[0], 2.0, rtol=1e-6)


def test_trimmed_mean_tiny_cohort_guard():
    """n=2 with trim_ratio=0.5 would trim everything without the
    k <= (n-1)//2 guard; the slice must stay non-empty."""
    x = jnp.asarray([[1.0], [3.0]])
    got = trimmed_mean({"v": x}, trim_ratio=0.5)
    assert np.isfinite(np.asarray(got["v"])).all()
    np.testing.assert_allclose(np.asarray(got["v"])[0], 2.0)


def test_cross_silo_weak_dp_rng_fresh_per_round():
    """The cross-silo aggregator used to call the weak_dp defense without an
    rng (ValueError on round 0); now it folds a per-aggregation key from the
    run seed, so noise is fresh every round and seeded-reproducible."""
    from types import SimpleNamespace

    from fedml_tpu.cross_silo.aggregator import FedMLAggregator

    def build():
        args = SimpleNamespace(defense_type="weak_dp", norm_bound=100.0,
                               stddev=0.1, random_seed=0)
        return FedMLAggregator(
            None, None, 16, 2, args, {"w": jnp.zeros(400, jnp.float32)})

    agg = build()
    delta = {"w": np.ones(400, np.float32)}
    agg.add_local_trained_result(0, delta, 8)
    agg.add_local_trained_result(1, delta, 8)
    p1 = np.asarray(agg.aggregate()["w"])
    agg.add_local_trained_result(0, delta, 8)
    agg.add_local_trained_result(1, delta, 8)
    p2 = np.asarray(agg.aggregate()["w"])
    n1, n2 = p1 - 1.0, (p2 - p1) - 1.0
    assert 0.05 < n1.std() < 0.2, n1.std()
    assert not np.allclose(n1, n2)  # fresh key per round
    # seeded determinism: a rebuilt aggregator replays the same noise
    agg_b = build()
    agg_b.add_local_trained_result(0, delta, 8)
    agg_b.add_local_trained_result(1, delta, 8)
    np.testing.assert_array_equal(p1, np.asarray(agg_b.aggregate()["w"]))


def test_lagrange_interpolation_identity():
    # encoding at the defining points returns the secret rows
    X = np.arange(12, dtype=np.int64).reshape(3, 4) % DEFAULT_PRIME
    betas = [1, 2, 3]
    out = lcc_encode(X, betas, betas)
    np.testing.assert_array_equal(out, X)


def test_lcc_encode_decode_roundtrip():
    rng = np.random.RandomState(0)
    X = rng.randint(0, DEFAULT_PRIME, size=(4, 6)).astype(np.int64)
    alphas = [11, 12, 13, 14]       # secret points
    betas = [1, 2, 3, 4, 5, 6]      # share points
    shares = lcc_encode(X, betas, alphas)
    # any 4 of the 6 shares reconstruct
    keep = [0, 2, 3, 5]
    recon = lcc_decode(shares[keep], [betas[i] for i in keep], alphas)
    np.testing.assert_array_equal(recon, X)


def test_modular_inv():
    for a in (2, 17, 123456789):
        assert (a * modular_inv(a)) % DEFAULT_PRIME == 1


def test_quantize_dequantize_roundtrip():
    tree = {"w": np.array([[0.5, -0.25], [1.5, 0.0]], np.float32), "b": np.array([-3.0], np.float32)}
    vec = quantize_tree(tree, q_bits=16)
    out = dequantize_tree(vec, tree, q_bits=16)
    np.testing.assert_allclose(out["w"], tree["w"], atol=1e-4)
    np.testing.assert_allclose(out["b"], tree["b"], atol=1e-4)


def test_lightsecagg_end_to_end_sum():
    n = 6
    updates = [
        {"w": np.full((5,), 0.1 * (i + 1), np.float32), "b": np.array([float(i)], np.float32)}
        for i in range(n)
    ]
    cfg = LightSecAggConfig(
        num_clients=n, target_active=4, privacy_guarantee=1,
        model_dimension=6, q_bits=12,
    )
    active = [0, 2, 3, 5]
    agg = secure_aggregate(updates, cfg, active, seed=42)
    expected_w = sum(updates[i]["w"] for i in active)
    expected_b = sum(updates[i]["b"] for i in active)
    np.testing.assert_allclose(agg["w"], expected_w, atol=1e-2)
    np.testing.assert_allclose(agg["b"], expected_b, atol=1e-2)


def test_dp_schedule_respects_memory_and_balances():
    assignment, costs = dp_schedule(
        workloads=[10, 10, 10, 1, 1, 1], constraints=[1.0, 1.0], memory=[100, 100]
    )
    assert sorted(i for a in assignment for i in a) == list(range(6))
    assert abs(costs[0] - costs[1]) <= 10


def test_dp_schedule_infeasible_raises():
    import pytest

    with pytest.raises(ValueError):
        dp_schedule([100], [1.0], [10])


def test_even_schedule_matches_array_split():
    shards = even_client_schedule([3, 1, 4, 1, 5, 9, 2], 3)
    np.testing.assert_array_equal(shards[0], [3, 1, 4])
    assert sum(len(s) for s in shards) == 7


def test_balanced_schedule_rectangular():
    shards = balanced_client_schedule(
        [0, 1, 2, 3, 4], sample_counts=[100, 1, 1, 1, 1], n_shards=2
    )
    widths = {len(s) for s in shards}
    assert len(widths) == 1  # rectangular
    covered = {int(i) for s in shards for i in s}
    assert covered == {0, 1, 2, 3, 4}
