"""Robust aggregation defenses + LCC secure aggregation + scheduler."""

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.core.robust import (
    RobustAggregator,
    coordinate_median,
    global_norm,
    norm_clip_update,
)
from fedml_tpu.core.scheduler import balanced_client_schedule, dp_schedule, even_client_schedule
from fedml_tpu.core.secure_agg import (
    DEFAULT_PRIME,
    LightSecAggConfig,
    dequantize_tree,
    lagrange_coeffs,
    lcc_decode,
    lcc_encode,
    modular_inv,
    quantize_tree,
    secure_aggregate,
)


def _stack(trees):
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *trees)


def test_norm_clip_bounds_update_norm():
    update = {"w": jnp.full((10,), 3.0), "b": jnp.ones(())}
    clipped = norm_clip_update(update, norm_bound=1.0)
    assert float(global_norm(clipped)) <= 1.0 + 1e-5
    # direction preserved
    ratio = clipped["w"][0] / clipped["b"]
    assert np.isclose(float(ratio), 3.0, rtol=1e-5)


def test_norm_clip_passthrough_below_bound():
    update = {"w": jnp.full((4,), 0.1)}
    clipped = norm_clip_update(update, norm_bound=10.0)
    np.testing.assert_allclose(np.asarray(clipped["w"]), 0.1, rtol=1e-6)


def test_coordinate_median_rejects_outlier():
    honest = [{"w": jnp.ones(5) * v} for v in (0.9, 1.0, 1.1)]
    byzantine = {"w": jnp.ones(5) * 1e6}
    stacked = _stack(honest + [byzantine])
    agg = coordinate_median(stacked)
    np.testing.assert_allclose(np.asarray(agg["w"]), 1.05, rtol=1e-5)


def test_robust_aggregator_weak_dp_noise_scale():
    ra = RobustAggregator(defense_type="weak_dp", norm_bound=100.0, stddev=0.1)
    stacked = {"w": jnp.ones((8, 1000))}
    agg = ra.aggregate(stacked, jnp.ones(8), rng=jax.random.PRNGKey(0))
    noise = np.asarray(agg["w"]) - 1.0
    assert 0.05 < noise.std() < 0.2


def test_lagrange_interpolation_identity():
    # encoding at the defining points returns the secret rows
    X = np.arange(12, dtype=np.int64).reshape(3, 4) % DEFAULT_PRIME
    betas = [1, 2, 3]
    out = lcc_encode(X, betas, betas)
    np.testing.assert_array_equal(out, X)


def test_lcc_encode_decode_roundtrip():
    rng = np.random.RandomState(0)
    X = rng.randint(0, DEFAULT_PRIME, size=(4, 6)).astype(np.int64)
    alphas = [11, 12, 13, 14]       # secret points
    betas = [1, 2, 3, 4, 5, 6]      # share points
    shares = lcc_encode(X, betas, alphas)
    # any 4 of the 6 shares reconstruct
    keep = [0, 2, 3, 5]
    recon = lcc_decode(shares[keep], [betas[i] for i in keep], alphas)
    np.testing.assert_array_equal(recon, X)


def test_modular_inv():
    for a in (2, 17, 123456789):
        assert (a * modular_inv(a)) % DEFAULT_PRIME == 1


def test_quantize_dequantize_roundtrip():
    tree = {"w": np.array([[0.5, -0.25], [1.5, 0.0]], np.float32), "b": np.array([-3.0], np.float32)}
    vec = quantize_tree(tree, q_bits=16)
    out = dequantize_tree(vec, tree, q_bits=16)
    np.testing.assert_allclose(out["w"], tree["w"], atol=1e-4)
    np.testing.assert_allclose(out["b"], tree["b"], atol=1e-4)


def test_lightsecagg_end_to_end_sum():
    n = 6
    updates = [
        {"w": np.full((5,), 0.1 * (i + 1), np.float32), "b": np.array([float(i)], np.float32)}
        for i in range(n)
    ]
    cfg = LightSecAggConfig(
        num_clients=n, target_active=4, privacy_guarantee=1,
        model_dimension=6, q_bits=12,
    )
    active = [0, 2, 3, 5]
    agg = secure_aggregate(updates, cfg, active, seed=42)
    expected_w = sum(updates[i]["w"] for i in active)
    expected_b = sum(updates[i]["b"] for i in active)
    np.testing.assert_allclose(agg["w"], expected_w, atol=1e-2)
    np.testing.assert_allclose(agg["b"], expected_b, atol=1e-2)


def test_dp_schedule_respects_memory_and_balances():
    assignment, costs = dp_schedule(
        workloads=[10, 10, 10, 1, 1, 1], constraints=[1.0, 1.0], memory=[100, 100]
    )
    assert sorted(i for a in assignment for i in a) == list(range(6))
    assert abs(costs[0] - costs[1]) <= 10


def test_dp_schedule_infeasible_raises():
    import pytest

    with pytest.raises(ValueError):
        dp_schedule([100], [1.0], [10])


def test_even_schedule_matches_array_split():
    shards = even_client_schedule([3, 1, 4, 1, 5, 9, 2], 3)
    np.testing.assert_array_equal(shards[0], [3, 1, 4])
    assert sum(len(s) for s in shards) == 7


def test_balanced_schedule_rectangular():
    shards = balanced_client_schedule(
        [0, 1, 2, 3, 4], sample_counts=[100, 1, 1, 1, 1], n_shards=2
    )
    widths = {len(s) for s in shards}
    assert len(widths) == 1  # rectangular
    covered = {int(i) for s in shards for i in s}
    assert covered == {0, 1, 2, 3, 4}
