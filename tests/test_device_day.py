"""Cross-device day driver (tentpole): the 1M-class device registry, the
seeded diurnal arrival curve, the virtual-time admission edge, and the full
churn drill — every claim here is either an accounting-closure invariant
(arrivals = offered + blackholed, offered = accepted + shed-by-reason, ...)
or a bit-identical-replay claim from ``(seed, curve)``.
"""

import dataclasses

import numpy as np
import pytest

from fedml_tpu.core import telemetry
from fedml_tpu.core.tenancy import CheckinQueue
from fedml_tpu.cross_device import (
    DEVICE_DAY_DEFAULTS,
    DeviceDayConfig,
    DeviceRegistry,
    run_device_churn_drill,
    run_device_day,
)
from fedml_tpu.cross_device.device_day import config_from_args
from fedml_tpu.cross_silo.loadgen import DiurnalCurve
from fedml_tpu.simulation.async_engine import VirtualEventHeap


@pytest.fixture(autouse=True)
def _fresh_telemetry():
    telemetry.configure(enabled=True, reset=True)
    yield
    telemetry.configure(enabled=True, reset=True)


def _tiny(**overrides):
    base = dict(
        registry_size=2_000, day_s=600.0, tick_s=30.0, num_classes=4,
        cohort=16, queue_maxsize=128, peak_rate=8.0, arena_capacity=64,
        host_capacity=128, eval_every_ticks=4, dropout_rate=0.05,
        max_commits_per_tick=2, seed=7,
    )
    base.update(overrides)
    return DeviceDayConfig(**base)


# --- registry lifecycle -------------------------------------------------------


def test_registry_lifecycle_and_counters():
    reg = DeviceRegistry(100, num_classes=4, seed=3)
    assert reg.state_counts()["eligible"] == 100
    ids = np.arange(10)
    reg.mark_checked_in(ids)
    assert reg.state_counts()["checked_in"] == 10
    assert not reg.admissible(ids).any()          # already in: refused
    reg.mark_training(ids[:6])
    reg.mark_uploaded(ids[:4], version=5)
    assert (reg.last_version[:4] == 5).all()
    assert reg.state_counts()["eligible"] == 94
    # mid-round failures: the two still-training devices drop
    assert reg.mark_dropped(ids[4:6]) == 2
    # devices the round plane passed over go back to eligible, not dropped
    reg.release(ids[6:10])
    assert reg.state_counts() == {
        "eligible": 98, "checked_in": 0, "training": 0,
        "dropped": 2, "departed": 0}
    assert reg.counters["checkins"] == 10
    assert reg.counters["uploads"] == 4
    assert reg.counters["dropouts"] == 2


def test_registry_rejoin_resync_split_on_log_floor():
    reg = DeviceRegistry(20, seed=0)
    reg.mark_checked_in(np.arange(8))
    reg.mark_uploaded(np.arange(4), version=2)    # behind the floor
    reg.mark_uploaded(np.arange(4, 8), version=9)  # ahead of it
    reg.mark_dropped(np.arange(8), held=True)
    assert reg.recover(rate=1.0, rng=np.random.default_rng(0)) == 0  # held
    out = reg.rejoin(np.arange(8), log_floor_version=5)
    assert out == {"rejoined": 8, "resync_full": 4, "resync_incremental": 4}
    assert (reg.state[:8] == 0).all() and not reg.held[:8].any()


def test_registry_departure_is_permanent():
    reg = DeviceRegistry(10, seed=0)
    gone = reg.depart([3, 4])
    assert sorted(gone.tolist()) == [3, 4]
    # departed devices never re-enter any lifecycle path
    assert reg.depart([3]).size == 0
    assert reg.mark_dropped([3]) == 0
    assert not reg.admissible([3, 4]).any()
    assert reg.eligible_available(0.0).size <= 8
    assert reg.counters["departures"] == 2


def test_registry_availability_is_seeded_and_windowed():
    a = DeviceRegistry(5_000, seed=11)
    b = DeviceRegistry(5_000, seed=11)
    np.testing.assert_array_equal(a.awake_start, b.awake_start)
    # awake windows are 0.3-0.9 of the day, so the fleet-wide availability
    # fraction at any instant sits inside that envelope
    frac = a.available(12_345.0).mean()
    assert 0.3 < frac < 0.9
    assert DeviceRegistry(5_000, seed=12).available(12_345.0).mean() != frac


# --- diurnal curve ------------------------------------------------------------


def test_diurnal_curve_pure_and_seeded():
    c = DiurnalCurve(peak_rate=10.0, seed=4)
    t = np.linspace(0.0, 86_400.0, 97)
    np.testing.assert_array_equal(c.rate(t),
                                  DiurnalCurve(peak_rate=10.0, seed=4).rate(t))
    assert (c.rate(t) >= 0.0).all()
    # peak-to-trough swing is real: the curve spans several-fold
    assert c.rate(t).max() > 2.5 * c.rate(t).min()
    # a different seed reshapes the harmonics but not the envelope
    d = DiurnalCurve(peak_rate=10.0, seed=5)
    assert not np.array_equal(c.rate(t), d.rate(t))
    # Poisson arrivals are owned by the caller's generator: same stream in,
    # same counts out
    n1 = [c.arrivals(i * 600.0, (i + 1) * 600.0,
                     np.random.default_rng([4, i])) for i in range(16)]
    n2 = [c.arrivals(i * 600.0, (i + 1) * 600.0,
                     np.random.default_rng([4, i])) for i in range(16)]
    assert n1 == n2 and sum(n1) > 0


def test_virtual_event_heap_pops_ties_in_push_order():
    h = VirtualEventHeap()
    for i, vt in enumerate([3.0, 1.0, 3.0, 1.0, 2.0]):
        h.push(vt, i)
    assert len(h) == 5
    assert h.peek_vt() == 1.0
    assert h.pop_batch() == (1.0, [1, 3])
    assert h.pop_batch() == (2.0, [4])
    assert h.pop_batch() == (3.0, [0, 2])
    assert not h


# --- the day itself -----------------------------------------------------------


def test_device_day_accounting_closes_and_replays_bit_identical():
    r1 = run_device_day(_tiny())
    assert r1.ok, r1.summary()
    assert r1.arrivals == r1.offered  # no partition in the plain day
    assert r1.offered == (r1.accepted + r1.shed_queue_full
                          + r1.shed_inadmissible)
    assert r1.commits > 0 and r1.committed_updates > 0
    assert r1.final_version == r1.commits - r1.zero_survivor_commits
    assert r1.duplicates == 0
    assert 0.0 <= r1.final_acc <= 1.0
    # bit-identical replay from (seed, curve): digests AND raw history
    r2 = run_device_day(_tiny())
    assert r2.history_digest == r1.history_digest
    assert r2.params_digest == r1.params_digest
    assert r2.history == r1.history
    # a different seed is a different day
    assert run_device_day(_tiny(seed=8)).history_digest != r1.history_digest


def test_device_day_spill_tier_engages_and_stays_bounded(tmp_path):
    cfg = _tiny(arena_capacity=24, host_capacity=48,
                spill_dir=str(tmp_path / "spill"))
    r = run_device_day(cfg)
    assert r.ok, r.summary()
    assert r.arena_resident <= cfg.arena_capacity
    assert r.arena_spilled > 0, "day never exercised the spill tier"
    assert len(list((tmp_path / "spill").glob("client_*.msgpack"))) > 0


def test_device_day_duplicate_announces_shed_as_inadmissible():
    # long announce latency relative to the tick makes re-announces while
    # the first copy is still airborne common — the edge must admit only
    # the first copy per wave and refuse the rest
    r = run_device_day(_tiny(arrival_spread_ticks=4.0, peak_rate=16.0))
    assert r.ok, r.summary()
    assert r.shed_inadmissible > 0
    assert r.duplicates == 0


def test_device_day_sheds_instead_of_unbounded_queue():
    r = run_device_day(_tiny(queue_maxsize=16, peak_rate=24.0))
    assert r.ok, r.summary()
    assert r.shed_queue_full > 0
    assert r.max_queue_depth <= 16
    cs = telemetry.get_registry().snapshot()["counters"]
    by_reason = {
        "queue_full": sum(v for k, v in cs.items()
                          if k.startswith("fedml_shed_total{reason=queue_full")),
        "inadmissible": sum(
            v for k, v in cs.items()
            if k.startswith("fedml_shed_total{reason=inadmissible")),
    }
    assert by_reason["queue_full"] == r.shed_queue_full
    assert by_reason["inadmissible"] == r.shed_inadmissible


def test_device_day_defaults_flow_through_args():
    class _Args:
        pass

    args = _Args()
    for key, val in DEVICE_DAY_DEFAULTS.items():
        setattr(args, key, val)
    args.device_registry_size = 123
    args.churn_fraction = 0.25
    cfg = config_from_args(args)
    assert cfg.registry_size == 123
    assert cfg.churn_fraction == 0.25
    assert cfg.spill_dir is None  # "" means no disk tier
    assert cfg.n_ticks == int(round(cfg.day_s / cfg.tick_s))


# --- the churn drill ----------------------------------------------------------


def test_churn_drill_survives_thirty_percent_churn(tmp_path):
    cfg = _tiny(registry_size=4_000, day_s=900.0, tick_s=30.0,
                cohort=24, peak_rate=12.0,
                churn_fraction=0.3, churn_rejoin_ticks=2,
                churn_permanent_fraction=0.2,
                churn_partition_classes=1, churn_partition_ticks=4,
                spill_dir=str(tmp_path))
    drill = run_device_churn_drill(cfg, max_acc_delta=0.05)
    assert drill.ok, drill.summary()
    c = drill.churned
    # every churn mechanism actually fired
    assert c.dropouts > 0 and c.rejoins > 0 and c.departures > 0
    assert c.partition_blackholed > 0
    assert c.reclaimed_spill_files > 0, \
        "permanent departures must reclaim their spill files"
    # the reference day is genuinely churn-free
    assert drill.reference.departures == 0
    assert drill.reference.partition_blackholed == 0
    # degradation is graceful, not catastrophic
    assert drill.acc_delta <= 0.05
    # and the churned day replays bit-identically
    assert drill.replay_identical


def test_churn_rejoin_across_version_log_trim_forces_full_resync():
    # keep only the last 2 versions; the churn wave drops at the midpoint
    # and rejoins several commits later, so rejoiners' last-synced version
    # has fallen off the retained log -> full resync, no duplicate commits
    cfg = _tiny(registry_size=4_000, day_s=900.0, tick_s=30.0,
                cohort=24, peak_rate=12.0, keep_versions=2,
                churn_fraction=0.4, churn_rejoin_ticks=4)
    r = run_device_day(cfg)
    assert r.ok, r.summary()
    assert r.rejoins > 0
    assert r.resync_full > 0, \
        "rejoin after the trim boundary must trigger full resyncs"
    assert r.resync_full + r.resync_incremental == r.rejoins
    assert r.duplicates == 0
