"""Every committed example config must stay loadable: the YAML parses
through the real argument loader AND its dataset/model pair resolves
through the factories (catches config rot when names change)."""

import glob
import os

import pytest

import fedml_tpu
from fedml_tpu.arguments import load_arguments

EXAMPLES = os.path.join(os.path.dirname(__file__), "..", "examples")
CONFIGS = sorted(
    glob.glob(os.path.join(EXAMPLES, "*", "*.yaml"))
    + glob.glob(os.path.join(EXAMPLES, "*", "*.yml"))
)


@pytest.mark.parametrize("cfg", CONFIGS, ids=lambda p: os.path.relpath(p, EXAMPLES))
def test_example_config_loads_and_resolves(cfg):
    args = load_arguments(args_list=["--cf", cfg])
    args.debug_small_data = True
    args = fedml_tpu.init(args=args)
    assert getattr(args, "dataset", None), cfg

    from fedml_tpu import data as data_mod
    from fedml_tpu import models as models_mod

    fed, output_dim = data_mod.load(args)
    model_name = getattr(args, "model", None)
    if model_name:  # some examples (cheetah/pipeline LM) build models inline
        model = models_mod.create(args, output_dim)
        assert model is not None
    assert fed.client_num >= 1


def test_examples_index_lists_every_directory():
    """examples/README.md must mention every example directory."""
    with open(os.path.join(EXAMPLES, "README.md")) as f:
        text = f.read()
    for d in sorted(os.listdir(EXAMPLES)):
        full = os.path.join(EXAMPLES, d)
        if os.path.isdir(full):
            assert f"`{d}/`" in text, f"examples/README.md missing {d}/"
