"""Unit tests: partitioner semantics, packing, collectives, config system."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P
from jax import shard_map

from fedml_tpu.arguments import Arguments, load_arguments
from fedml_tpu.core.partition import (
    homo_partition,
    non_iid_partition_with_dirichlet_distribution,
)
from fedml_tpu.data.federated import ArrayPair, build_federated_data
from fedml_tpu.data.synthetic import make_classification_like
from fedml_tpu.parallel import (
    AXIS_CLIENT,
    MeshConfig,
    create_mesh,
    psum_tree,
    ring_neighbors,
    weighted_psum_tree,
)


def test_dirichlet_partition_covers_all_samples():
    np.random.seed(0)
    labels = np.random.randint(0, 10, 1000)
    m = non_iid_partition_with_dirichlet_distribution(labels, 13, 10, 0.5)
    all_idx = sorted(i for v in m.values() for i in v)
    assert all_idx == list(range(1000))
    assert min(len(v) for v in m.values()) >= 10


def test_dirichlet_partition_seeded_reproducible():
    labels = np.tile(np.arange(10), 100)
    np.random.seed(7)
    m1 = non_iid_partition_with_dirichlet_distribution(labels, 5, 10, 0.3)
    np.random.seed(7)
    m2 = non_iid_partition_with_dirichlet_distribution(labels, 5, 10, 0.3)
    assert all(m1[k] == m2[k] for k in m1)


def test_homo_partition_even():
    np.random.seed(0)
    m = homo_partition(100, 7)
    sizes = [len(v) for v in m.values()]
    assert sum(sizes) == 100 and max(sizes) - min(sizes) <= 1


def test_pack_clients_masks_padding():
    tr, te = make_classification_like(100, 20, (4,), 3, seed=1)
    np.random.seed(0)
    fed = build_federated_data(tr, te, homo_partition(100, 4), 3)
    pk = fed.pack_clients([0, 1, 2, 3], batch_size=8, num_batches=5)
    assert pk.x.shape == (4, 5, 8, 4)
    for i in range(4):
        assert pk.mask[i].sum() == pk.num_samples[i]


def test_weighted_psum_matches_numpy():
    mesh = create_mesh(MeshConfig(axes=((AXIS_CLIENT, 8),)))
    x = jnp.arange(8.0)
    w = jnp.linspace(0.1, 0.8, 8)

    def f(xs, ws):
        return weighted_psum_tree(xs, ws[0], AXIS_CLIENT)

    out = shard_map(
        f, mesh=mesh, in_specs=(P(AXIS_CLIENT), P(AXIS_CLIENT)), out_specs=P(AXIS_CLIENT)
    )(x, w)
    expected = float((np.arange(8.0) * np.linspace(0.1, 0.8, 8)).sum())
    assert np.allclose(np.asarray(out), expected)


def test_ring_neighbors():
    assert ring_neighbors(4) == [(0, 1), (1, 2), (2, 3), (3, 0)]


def test_arguments_yaml_roundtrip(tmp_path):
    cfg = tmp_path / "c.yaml"
    cfg.write_text(
        """
common_args:
  training_type: simulation
  random_seed: 3
train_args:
  learning_rate: 0.05
  client_num_in_total: 7
"""
    )
    args = load_arguments(args_list=["--cf", str(cfg)])
    assert args.random_seed == 3
    assert args.learning_rate == 0.05
    assert args.client_num_in_total == 7


def test_arguments_collision_raises(tmp_path):
    cfg = tmp_path / "c.yaml"
    cfg.write_text(
        """
train_args:
  batch_size: 4
data_args:
  batch_size: 8
"""
    )
    with pytest.raises(ValueError, match="batch_size"):
        load_arguments(args_list=["--cf", str(cfg)])
