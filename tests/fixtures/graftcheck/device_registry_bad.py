"""Fixture: a cross-device check-in gateway with the two concurrency
mistakes the cross_device scope exists to catch (fed to the checkers under
a ``fedml_tpu/cross_device/`` relpath — see tests/test_static_analysis.py):
blocking work under the admission lock (and AB/BA nesting against the
registry lock), plus a heartbeat thread racing the main thread on shared
fleet state with no common lock."""

import threading
import time


class Gateway:
    def __init__(self):
        self._admit_lock = threading.Lock()
        self._fleet_lock = threading.Lock()
        self.last_checkin = None

    def admit(self, sock, frame):
        with self._admit_lock:
            with self._fleet_lock:
                sock.sendall(frame)    # blocking send under both locks

    def evict(self):
        # opposite nesting order from admit() — AB/BA deadlock
        with self._fleet_lock:
            with self._admit_lock:
                time.sleep(0.5)

    def start_heartbeats(self):
        threading.Thread(target=self._beat, daemon=True).start()

    def _beat(self):
        while True:
            self.last_checkin = time.monotonic()  # unlocked thread write

    def stale(self):
        return self.last_checkin       # unlocked main-thread read
