"""Fixture: serving-plane hazards (fed to the checkers under a
``fedml_tpu/serving/`` relpath — see tests/test_static_analysis.py).
A promote that publishes while holding both store locks, an AB/BA
nesting between the swap and stats locks, and a serve-loop thread
mutating the active pointer and served-counts with no common lock."""

import threading
import time


class BadStore:
    def __init__(self):
        self._swap_lock = threading.Lock()
        self._stats_lock = threading.Lock()
        self._bus = None

    def promote(self, version):
        with self._swap_lock:
            with self._stats_lock:
                self._bus.publish(version)   # blocking publish under locks

    def stats(self):
        # opposite nesting order from promote() — the AB/BA deadlock
        with self._stats_lock:
            with self._swap_lock:
                time.sleep(0.01)


class BadServer:
    def __init__(self):
        self._lock = threading.Lock()
        self.active = None
        self._served = {}

    def start(self):
        t = threading.Thread(target=self._serve_loop, daemon=True)
        t.start()

    def _serve_loop(self):
        while True:
            self.active = self._next_version()   # unlocked write in thread
            self._served[self.active] = True

    def current(self):
        return self.active                       # unlocked read from main

    def served_by_version(self):
        with self._lock:                         # reader locks, writer doesn't
            return dict(self._served)

    def _next_version(self):
        return 1
