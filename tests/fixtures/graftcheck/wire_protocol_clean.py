"""Fixture: a conformant wire protocol — every sent type handled, every
handler-read key stamped by a sender of that type, constants everywhere."""


class Message:
    MSG_ARG_KEY_TYPE = "msg_type"
    MSG_TYPE_SYNC = "sync"
    MSG_ARG_KEY_MODEL_PARAMS = "model_params"
    MSG_ARG_KEY_ROUND_INDEX = "round_idx"

    def __init__(self, type=None, sender_id=0, receiver_id=0):
        self.params = {Message.MSG_ARG_KEY_TYPE: type}

    def add_params(self, key, value):
        self.params[key] = value

    def get(self, key, default=None):
        return self.params.get(key, default)

    def get_type(self):
        return self.params.get(Message.MSG_ARG_KEY_TYPE)


MSG_TYPE_SHARED = "shared_event"


class GoodServer:
    def send_sync(self, comm):
        msg = Message(type=Message.MSG_TYPE_SYNC, sender_id=0, receiver_id=1)
        msg.add_params(Message.MSG_ARG_KEY_MODEL_PARAMS, {})
        msg.add_params(Message.MSG_ARG_KEY_ROUND_INDEX, 3)
        comm.send_message(msg)

    def send_shared(self, comm):
        comm.send_message(Message(type=MSG_TYPE_SHARED))


class GoodClient:
    def register(self):
        self.register_message_receive_handler(
            Message.MSG_TYPE_SYNC, self.handle_sync)
        self.register_message_receive_handler(
            MSG_TYPE_SHARED, self.handle_shared)

    def register_message_receive_handler(self, msg_type, handler):
        pass

    def handle_sync(self, msg):
        params = msg.get(Message.MSG_ARG_KEY_MODEL_PARAMS)
        round_idx = msg.get(Message.MSG_ARG_KEY_ROUND_INDEX)
        # a defaulted read never requires a stamp
        maybe = msg.get("optional_hint", None)
        return params, round_idx, maybe

    def handle_shared(self, msg):
        return msg.get_type()
