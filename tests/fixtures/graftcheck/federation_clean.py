"""Fixture: the safe twin of federation_bad — the round counter is only
touched through lock-guarded accessors shared by both threads, and the
root takes lease-table before commit-ledger on every path, sleeping
outside the critical section."""

import threading
import time


class CleanLeafWorker:
    def __init__(self):
        self._round_lock = threading.Lock()
        self._round = 0

    def start(self):
        t = threading.Thread(target=self._heartbeat_loop, daemon=True)
        t.start()

    def _heartbeat_loop(self):
        while True:
            self._send_heartbeat(self._current_round())

    def on_dispatch(self, msg):
        self._set_round(msg.round_idx)

    def _current_round(self):
        with self._round_lock:
            return self._round

    def _set_round(self, round_idx):
        with self._round_lock:
            self._round = round_idx

    def _send_heartbeat(self, round_idx):
        return None


class CleanRootCoordinator:
    def __init__(self):
        self._lease_lock = threading.Lock()
        self._ledger_lock = threading.Lock()

    def dispatch(self, round_idx):
        with self._lease_lock:
            with self._ledger_lock:
                pass
        time.sleep(0.1)

    def failover(self, dead_rank):
        # same nesting order as dispatch()
        with self._lease_lock:
            with self._ledger_lock:
                pass
