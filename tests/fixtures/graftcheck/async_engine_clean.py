"""Fixture: the safe twin of async_engine_bad — every buffer/version
access from either thread happens under the one lock, and the delay
plan's RNG stream is derived from the run seed, so a replay with the
same seed sees the same schedule."""

import threading

import numpy as np


class CleanAsyncServer:
    def __init__(self, seed):
        self._lock = threading.Lock()
        self._buffer = []
        self._version = 0
        self._rng = np.random.default_rng((int(seed), 0xA5))

    def start(self):
        t = threading.Thread(target=self._ingest_loop, daemon=True)
        t.start()

    def _ingest_loop(self):
        while True:
            update = self._recv()
            with self._lock:
                self._buffer.append(update)
                self._version = self._version + 1

    def commit(self):
        with self._lock:
            batch = list(self._buffer)
            self._buffer = []
            return batch, self._version

    def next_delay(self):
        return self._rng.exponential()

    def _recv(self):
        return None
