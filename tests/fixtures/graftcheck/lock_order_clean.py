"""Fixture: consistent lock order, no blocking work under locks."""

import threading
import time


class Channel:
    def __init__(self):
        self._send_lock = threading.Lock()
        self._state_lock = threading.Lock()

    def send(self, sock, payload):
        with self._send_lock:
            with self._state_lock:
                self._pending = payload
        sock.sendall(payload)

    def close(self):
        # same order as send()
        with self._send_lock:
            with self._state_lock:
                self._pending = None
        time.sleep(0.1)
