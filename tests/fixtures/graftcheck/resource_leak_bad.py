"""Fixture: every resource-leak shape the checker must flag."""
import json
import socket
import threading

import grpc

from fedml_tpu.simulation.client_store import ClientStateArena


def thread_never_joined(work):
    t = threading.Thread(target=work)
    t.start()
    return "done"  # t outlives the function, neither daemon nor joined


def inline_thread(work):
    threading.Thread(target=work).start()  # no handle to join at all


def unclosed_file(path):
    f = open(path)
    data = f.read()
    return len(data)  # fd leaks on every call


def inline_open(path):
    data = open(path).read()
    return json.loads(data)


def unclosed_socket(host, port):
    s = socket.socket()
    s.connect((host, port))
    s.sendall(b"ping")


def unclosed_channel(target):
    ch = grpc.insecure_channel(target)
    ch.unary_unary("/svc/Method")


def spill_without_reclaim(proto, tmpdir):
    return ClientStateArena(proto, 64, spill_dir=tmpdir)
