"""Fixture: pure jit-traced code plus impure code OUTSIDE any trace."""

import time

import jax
import jax.numpy as jnp


@jax.jit
def step(x, key):
    noise = jax.random.normal(key, x.shape)
    return jnp.tanh(x) + 0.1 * noise


def timed_host_step(x, key):
    # host-side timing around the trace is fine — only traced bodies
    # must stay pure
    t0 = time.perf_counter()
    y = step(x, key)
    return y, time.perf_counter() - t0
