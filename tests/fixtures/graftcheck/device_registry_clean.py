"""Fixture: the same check-in gateway written with the house discipline —
one nesting order, no blocking call under any lock, and the heartbeat
thread sharing a lock with its readers."""

import threading
import time


class Gateway:
    def __init__(self):
        self._admit_lock = threading.Lock()
        self._fleet_lock = threading.Lock()
        self.last_checkin = None

    def admit(self, sock, frame):
        with self._admit_lock:
            with self._fleet_lock:
                self._pending = frame
        sock.sendall(frame)            # send happens outside the locks

    def evict(self):
        # same order as admit()
        with self._admit_lock:
            with self._fleet_lock:
                self._pending = None
        time.sleep(0.5)

    def start_heartbeats(self):
        threading.Thread(target=self._beat, daemon=True).start()

    def _beat(self):
        while True:
            with self._fleet_lock:
                self.last_checkin = time.monotonic()

    def stale(self):
        with self._fleet_lock:         # same lock as the writer
            return self.last_checkin
