"""Fixture: deterministic counterparts of determinism_bad."""

import random

import numpy as np


def make_rng(seed):
    return np.random.default_rng(seed)


def make_py_rng(seed):
    return random.Random(seed)


def cohort_order(client_ids):
    chosen = set(client_ids)
    return sorted(chosen)
