"""Fixture: deterministic counterparts of determinism_bad."""

import random

import numpy as np


def make_rng(seed):
    return np.random.default_rng(seed)


def make_py_rng(seed):
    return random.Random(seed)


def cohort_order(client_ids):
    chosen = set(client_ids)
    return sorted(chosen)


def quantize_seeded(vals, codec, seed, round_idx, client_id):
    return codec.stochastic_quantize(vals, 8, seed, round_idx, client_id)


def key_seeded(codec, seed):
    return codec.stochastic_key(seed, 0, 0)


def roundtrip_seeded(spec, codec, seed):
    return codec.build_stacked_roundtrip(spec, seed=seed)


def roundtrip_forwarded(spec, codec, **kw):
    # kwargs splat may carry the seed — not flaggable statically
    return codec.build_stacked_roundtrip(spec, **kw)


def spec_leaf_order(param_paths):
    distinct = set(param_paths)
    return sorted(distinct)
