"""Fixture: a device-resident scanned round body — the HOF-callback rule
roots it, finds nothing, and the surrounding cold ``_build_*`` factory's
own host staging stays unflagged (fed under the fed_sim.py relpath)."""

import jax
import jax.numpy as jnp
import numpy as np


class FedSimulator:
    def _build_scan_step(self, block_len, host_idx):
        # host staging in the cold factory itself is fine: the walk roots
        # only the callback, not its definition site
        xs_host = np.asarray(host_idx)

        def scan_round(carry, xs):
            params, state = carry
            grads = self._round_math(params, xs)
            return (params, jax.tree.map(jnp.add, state, grads)), grads

        def step(params, state, xs):
            return jax.lax.scan(scan_round, (params, state), xs,
                                length=block_len)

        return jax.jit(step), xs_host

    def _round_math(self, params, xs):
        return jnp.mean(xs)


def _build_loops(n):
    def body_fun(i, val):
        return val + jnp.float32(i)

    def cond_fun(val):
        return val < 3.0

    def while_body(val):
        return val * 2

    out = jax.lax.fori_loop(0, n, body_fun, jnp.zeros(()))
    return jax.lax.while_loop(cond_fun, while_body, out)
