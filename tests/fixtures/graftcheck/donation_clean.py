"""Fixture: donation used idiomatically — every donated buffer is either
rebound in the same statement, rebound before any later read, or never
read again. A non-donating jit imposes no restriction at all."""

import jax


def train_step(params, opt_state, batch):
    return params, opt_state


class Trainer:
    def __init__(self):
        self._step = jax.jit(train_step, donate_argnums=(0, 1))
        self._fwd = jax.jit(train_step)  # no donation

    def step(self, batch):
        # same-statement rebinding: the canonical safe shape
        self.params, self.opt_state = self._step(
            self.params, self.opt_state, batch)
        return self.params

    def rebound_before_read(self, batch):
        out = self._step(self.params, self.opt_state, batch)
        self.params = out[0]
        self.opt_state = out[1]
        return self.params  # read lands after the rebinding horizon

    def no_donation(self, batch):
        out = self._fwd(self.params, self.opt_state, batch)
        return out, self.params  # _fwd does not donate


def drive(weights, update):
    weights = jax.jit(train_step, donate_argnums=(0,))(
        weights, update, None)[0]
    return weights
