"""Bad fixture: a Pallas aggregation kernel module doing host work.

jit-purity must flag the kernel body — it is traced by ``pl.pallas_call``
exactly like a jit body (handed over through ``functools.partial``, the
idiomatic static-arg route), so host clocks/RNG/print bake trace-time
constants into every launch and ``.item()`` forces a sync mid-trace.

host-sync must flag the op wrapper when this module masquerades as
``fedml_tpu/ops/pallas/`` (every top-level def in a kernel module is an
entry point there): the explicit sync and the device->host copy stall
the aggregation hot path on every call.
"""
import functools
import time

import jax
import numpy as np
from jax.experimental import pallas as pl


def _agg_kernel(x_ref, o_ref, *, block):
    tile = x_ref[...]
    print("tile", tile)              # trace-time host I/O
    t = time.time()                  # host clock -> trace-time constant
    noise = np.random.rand(block)    # host RNG draw, constant-folded
    scale = tile.mean().item()       # host sync inside traced code
    o_ref[...] = tile * scale + noise + t


def fused_agg(x):
    out = pl.pallas_call(
        functools.partial(_agg_kernel, block=8),
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
    )(x)
    out.block_until_ready()          # serializes the op pipeline
    host = np.asarray(out)           # device->host copy per call
    return host
