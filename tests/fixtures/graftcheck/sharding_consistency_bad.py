"""Fixture: PartitionSpec axis names nothing declares, plus a hand-rolled
tree of literal specs that duplicates auto_partition_specs."""

import jax
from jax.sharding import PartitionSpec as P


def replicate_specs(params):
    # "clients" is a typo of the canonical "client" axis
    return P("clients", None)


def model_specs():
    # nested-tuple spec with a typo'd second axis
    return P(("data", "modle"))


def handrolled(params):
    # WARNING: literal P(...) per leaf — auto_partition_specs' job
    return jax.tree_util.tree_map(lambda x: P("data"), params)
