"""Fixture: use-after-donate violations in every resolution shape the
checker supports (direct binding, builder hop, decorator, inline)."""

from functools import partial

import jax


def train_step(params, opt_state, batch):
    return params, opt_state


def _round(state, grads):
    return state


class Trainer:
    def __init__(self):
        # direct binding: jit with donate_argnums
        self._step = jax.jit(train_step, donate_argnums=(0, 1))
        # builder hop: the donated jit is made one call away
        self._round = self._build_round_step()

    def _build_round_step(self):
        return jax.jit(_round, donate_argnums=(0,))

    def step_and_log(self, batch):
        out = self._step(self.params, self.opt_state, batch)
        # self.params was donated at position 0 and never rebound
        return out, self.params

    def advance(self, state, grads):
        result = self._round(state, grads)
        # state was donated through the builder-returned jit
        return result, state.shape


@partial(jax.jit, donate_argnums=(0,))
def apply_update(params, update):
    return params


def drive(weights, update):
    new = apply_update(weights, update)
    return new, weights  # weights donated to the decorated jit above


def inline(x, y):
    out = jax.jit(train_step, donate_argnums=(0,))(x, y, None)
    return out, x  # x donated to the inline jit call
