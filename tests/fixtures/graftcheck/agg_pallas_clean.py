"""Clean twin of agg_pallas_bad.py: same pallas_call structure, pure
kernel body, no syncs in the op wrapper — both checkers must stay silent
even with the module scoped as ``fedml_tpu/ops/pallas/``."""
import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _agg_kernel(x_ref, o_ref, *, block):
    tile = x_ref[...]
    o_ref[...] = tile * jnp.float32(block)


def fused_agg(x):
    return pl.pallas_call(
        functools.partial(_agg_kernel, block=8),
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
    )(x)
