"""Fixture: the two hazards the buffered-async engine must not grow
(fed to the checkers under the async_engine relpath). An ingest thread
folds arriving updates into the commit buffer with no lock against the
committer, and the straggler delay plan draws from an unseeded RNG —
the exact races/replay breaks thread-hazard and determinism guard."""

import threading

import numpy as np


class BadAsyncServer:
    def __init__(self):
        self._lock = threading.Lock()
        self._buffer = []
        self._version = 0
        # unseeded: every replay gets a different delay schedule
        self._rng = np.random.default_rng()

    def start(self):
        t = threading.Thread(target=self._ingest_loop, daemon=True)
        t.start()

    def _ingest_loop(self):
        while True:
            update = self._recv()
            self._buffer.append(update)      # unlocked write from the thread
            self._version = self._version + 1

    def commit(self):
        batch = list(self._buffer)           # unlocked read from main
        self._buffer = []                    # unlocked main-thread write
        return batch, self._version

    def next_delay(self):
        return self._rng.exponential()

    def _recv(self):
        return None
