"""Fixture: the serving-plane discipline the checkers enforce — one
short lock around the RCU swap and counters, telemetry/publish side
effects strictly after release, an Event-guarded run flag, and the
serve thread taking the same lock as every reader."""

import threading


class CleanStore:
    def __init__(self):
        self._lock = threading.Lock()
        self._active = None
        self._swaps = 0

    def promote(self, version, params):
        with self._lock:
            self._active = (version, params)     # RCU pointer swap
            self._swaps += 1
        self._emit(version)                      # side effects post-release

    def active(self):
        with self._lock:
            return self._active

    def stats(self):
        with self._lock:
            return {"swaps": self._swaps}

    def _emit(self, version):
        pass


class CleanServer:
    def __init__(self):
        self._lock = threading.Lock()
        self._run = threading.Event()
        self._served = {}

    def start(self):
        self._run.set()
        t = threading.Thread(target=self._serve_loop, daemon=True)
        t.start()

    def _serve_loop(self):
        while self._run.is_set():
            version = self._pump()
            with self._lock:
                self._served[version] = self._served.get(version, 0) + 1

    def stats(self):
        with self._lock:
            return dict(self._served)

    def stop(self):
        self._run.clear()

    def _pump(self):
        return 1
