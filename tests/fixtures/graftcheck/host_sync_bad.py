"""Fixture: implicit device syncs on the round loop (fed to the checker
under the fed_sim.py relpath so ``run`` is a hot entry point)."""

import jax
import numpy as np


class FedSimulator:
    def run(self, apply_fn):
        out = None
        for r in range(3):
            out = self._round(r)
            jax.block_until_ready(out)          # explicit sync per round
            loss = float(out["loss"].mean())    # scalar readback per round
        return out, loss

    def _round(self, r):
        metrics = self._step(r)
        m = np.asarray(metrics)                 # device->host copy
        v = metrics.item()                      # scalar readback
        jax.device_get(metrics)                 # bulk readback
        return m, v

    def _step(self, r):
        return r
