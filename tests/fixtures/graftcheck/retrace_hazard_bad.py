"""Fixture: every retrace-hazard shape the checker must flag."""
import jax
import jax.numpy as jnp
from functools import partial


def step(params, batch):
    return params


def jit_in_loop(params, batches):
    for batch in batches:
        f = jax.jit(step)  # fresh wrapper per iteration: empty trace cache
        params = f(params, batch)
    return params


def per_call_jit(params, batch):
    # constructed, invoked, and discarded on every call
    return jax.jit(step)(params, batch)


def discarded_jit(params):
    g = jax.jit(step)  # bound but never invoked, escaped, or returned
    return params


compiled = jax.jit(step, static_argnums=(1,))


def loop_varying_static(params, batches):
    for i, batch in enumerate(batches):
        params = compiled(params, i)  # static arg varies with the loop
    return params


def unhashable_static(params, batch):
    return compiled(params, [1, 2, 3])  # list literal can never hash


plain = jax.jit(step)


def shape_flow(params, batches):
    for batch in batches:
        params = plain(params, len(batch))  # len() respecializes per shape
    return params


def scan_block(params, cohorts):
    def body(carry, cohort):
        h = jax.jit(step)  # retrace here recompiles the whole fused block
        return h(carry, cohort), None

    out, _ = jax.lax.scan(body, params, cohorts)
    return out
