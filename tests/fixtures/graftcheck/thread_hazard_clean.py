"""Fixture: the safe cross-thread idioms — a common lock on both sides,
entry-lock propagation into helpers (self-call and nested plain-name
call), GIL-atomic flag flips, and internally-synchronized containers."""

import queue
import threading


class SafeWire:
    def __init__(self):
        self._lock = threading.Lock()
        self.status = None
        self._running = True
        self._q = queue.Queue()

    def start(self):
        threading.Thread(target=self._loop, daemon=True).start()

    def _loop(self):
        while self._running:           # reads a GIL-atomic flag
            msg = self._q.get()        # Queue synchronizes internally
            with self._lock:
                self.status = msg

    def stop(self):
        self._running = False          # constant flag flip: the idiom

    def poll(self):
        with self._lock:
            return self.status         # same lock as the writer

    def update(self, m):
        with self._lock:
            self._apply(m)

    def _apply(self, m):
        # only ever called with _lock held — entry-lock propagation
        self.status = m

    def wait_ready(self):
        def _ready():
            return self.status is not None

        with self._lock:
            while not _ready():        # nested helper called under the lock
                self._lock.release()
                self._lock.acquire()

    def push(self, m):
        self._q.put(m)
