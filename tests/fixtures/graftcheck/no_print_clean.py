"""Fixture: logging instead of print; print-as-value stays legal."""

import logging


def announce(round_idx, log_fn=print):
    logging.info("round %s done", round_idx)
    return log_fn
