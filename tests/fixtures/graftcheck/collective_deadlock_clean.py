"""Fixture: collectives under uniform guards, per-process branches without
collectives, and nested defs that reset the guard context."""

import jax
from jax import lax


def config_guard(x, cfg):
    if cfg.use_psum:  # same config on every participant
        return jax.lax.psum(x, "data")
    return x


def count_guard(x):
    if jax.process_count() > 1:  # uniform across the mesh
        return jax.lax.pmean(x, "data")
    return x


def rank_reporting(x, rank, log):
    if rank == 0:
        log("round done")  # divergent branch, but no collective inside
    return jax.lax.psum(x, "data")  # collective outside any guard


def make_step(rank):
    if rank == 0:
        def step(x):
            # new call boundary: the body does not run under the guard
            return jax.lax.psum(x, "data")
        return step
    return None
