"""Fixture: every PartitionSpec axis is either canonical (parallel/mesh.py
vocabulary) or declared by a mesh constructor in this module; tree_map
without literal specs stays legal."""

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def local_mesh(devices):
    # an ad-hoc mesh declares its own axis names for this module
    return Mesh(devices, ("rows", "cols"))


def local_spec():
    return P("rows", "cols")


def canonical_specs(mesh):
    # canonical axes from the framework vocabulary
    return NamedSharding(mesh, P("data", None)), P(("client", "model"))


def scaled(params):
    # tree_map without spec construction is not the spec layer's business
    return jax.tree_util.tree_map(lambda x: x * 2, params)
