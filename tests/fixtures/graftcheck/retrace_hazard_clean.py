"""Fixture: retrace-safe twins of every retrace_hazard_bad shape."""
import jax
import jax.numpy as jnp
from functools import partial


def step(params, batch):
    return params


compiled = jax.jit(step)  # constructed once at module scope


def loop_reuses_wrapper(params, batches):
    for batch in batches:
        params = compiled(params, batch)
    return params


class Engine:
    def __init__(self):
        # builder pattern: wrapper outlives the call that made it
        self._step = self._build_step()

    def _build_step(self):
        return jax.jit(step)

    def run(self, params, batches):
        for batch in batches:
            params = self._step(params, batch)
        return params


mode_step = jax.jit(step, static_argnums=(1,))


def loop_invariant_static(params, batches, mode):
    for batch in batches:
        params = mode_step(params, mode)  # static arg fixed across the loop
    return params


def escaped_wrapper(params):
    f = jax.jit(step)
    return f  # handed to the caller — their lifecycle now


def scan_block(params, cohorts):
    def body(carry, cohort):
        return compiled(carry, cohort), None

    out, _ = jax.lax.scan(body, params, cohorts)
    return out
