"""Fixture: lock-order violations (fed to the checker under a relpath
inside its comm/cross_silo scope — see tests/test_static_analysis.py)."""

import threading
import time


class Channel:
    def __init__(self):
        self._send_lock = threading.Lock()
        self._state_lock = threading.Lock()

    def send(self, sock, payload):
        with self._send_lock:
            with self._state_lock:
                sock.sendall(payload)

    def close(self):
        # opposite nesting order from send() — the classic AB/BA deadlock
        with self._state_lock:
            with self._send_lock:
                time.sleep(0.1)

    def reenter(self):
        with self._send_lock:
            with self._send_lock:
                pass
