"""Fixture: impure operations reachable from jit-traced code."""

import time
import random

import jax
import numpy as np


def _noise():
    # reached from the jitted body through the same-module call graph
    return np.random.normal()


@jax.jit
def step(x):
    t = time.time()
    print("stepping", t)
    r = random.random()
    return x + t + r + _noise()


def loop(xs):
    def body(carry, x):
        # lax.scan bodies are traced too
        return carry + time.monotonic(), x

    return jax.lax.scan(body, 0.0, xs)
