"""Fixture: leak-free twins of every resource_leak_bad shape."""
import json
import socket
import threading

import grpc

from fedml_tpu.simulation.client_store import ClientStateArena


def thread_joined(work):
    t = threading.Thread(target=work)
    t.start()
    t.join()


def daemon_thread(work):
    t = threading.Thread(target=work, daemon=True)
    t.start()


class Pool:
    def __init__(self, work):
        # escapes to self: the pool's shutdown owns the join
        self._t = threading.Thread(target=work)
        self._t.start()

    def handed_off(self, work, threads):
        threads.append(threading.Thread(target=work))


def with_file(path):
    with open(path) as f:
        return json.load(f)


def closed_socket(host, port):
    s = socket.socket()
    try:
        s.connect((host, port))
    finally:
        s.close()


def with_channel(target):
    with grpc.insecure_channel(target) as ch:
        ch.unary_unary("/svc/Method")


def returned_handle(path):
    return open(path)  # caller's lifecycle, not ours


def spill_with_reclaim(proto, tmpdir, departed):
    arena = ClientStateArena(proto, 64, spill_dir=tmpdir)
    arena.discard(departed)
    return arena
