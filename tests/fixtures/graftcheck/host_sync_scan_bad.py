"""Fixture: host round-trips inside a scanned round body. The body is
built by a cold ``_build_*`` factory (the walk never enters those), but
it is passed to ``lax.scan`` — the HOF-callback rule must root it
anyway, plus the fori/while callbacks (fed under the fed_sim.py
relpath)."""

import jax
import jax.numpy as jnp
import numpy as np


class FedSimulator:
    def _build_scan_step(self, block_len):
        def scan_round(carry, xs):
            params, state = carry
            out = self._round_math(params, xs)
            np.asarray(out)                     # device->host inside scan
            jax.block_until_ready(out)          # sync inside scan
            return (params, state), out

        def step(params, state, xs):
            return jax.lax.scan(scan_round, (params, state), xs,
                                length=block_len)

        return jax.jit(step)

    def _round_math(self, params, xs):
        # reachable FROM the scanned body via a plain call edge
        loss = jnp.mean(xs)
        return loss.item()                      # scalar readback


def _build_loops(n):
    def body_fun(i, val):
        return val + jax.device_get(val)        # bulk readback inside fori

    def cond_fun(val):
        return float(val.sum()) < 3.0           # scalar readback inside while

    def while_body(val):
        return val * 2

    out = jax.lax.fori_loop(0, n, body_fun, jnp.zeros(()))
    return jax.lax.while_loop(cond_fun, while_body, out)
