"""Fixture: a bare print() call in library code."""


def announce(round_idx):
    print(f"round {round_idx} done")
    return round_idx
