"""Fixture: a round loop that stays device-resident — readback only in
cold phase-boundary planes, placement-wrapped host conversions, and
host-container access (fed under the fed_sim.py relpath)."""

import jax
import numpy as np


class FedSimulator:
    def run(self, apply_fn):
        state = None
        for r in range(2):
            state = self._step(r)
        return self._eval_metrics(state)  # cold plane: readback is the point

    def _step(self, r):
        # host->device placement around asarray is not a sync
        arr = jax.device_put(np.asarray(self._host_buf), self._sharding)
        x = np.asarray(self._batches[0])  # host-container subscript
        scale = float(0.5)                # plain python scalar
        return arr, x, scale

    def _eval_metrics(self, state):
        return np.asarray(state)


def build_round_inputs(batches):
    # packing plane: host staging, never on the round loop
    return [np.asarray(b) for b in batches]
