"""Fixture: wire-protocol violations — unhandled send, unstamped handler
read, raw literal shadowing a constant, duplicated constant (paired with
wire_protocol_clean.py via the project graph when scanned together)."""


class Message:
    MSG_ARG_KEY_TYPE = "msg_type"
    MSG_TYPE_UPLOAD = "upload"
    MSG_ARG_KEY_MODEL_PARAMS = "model_params"
    MSG_ARG_KEY_NUM_SAMPLES = "num_samples"

    def __init__(self, type=None, sender_id=0, receiver_id=0):
        self.params = {Message.MSG_ARG_KEY_TYPE: type}

    def add_params(self, key, value):
        self.params[key] = value

    def get(self, key, default=None):
        return self.params.get(key, default)

    def get_type(self):
        return self.params.get(Message.MSG_ARG_KEY_TYPE)


MSG_TYPE_ORPHANED = "orphaned"
# duplicates the value defined in wire_protocol_clean.py under the same name
MSG_TYPE_SHARED = "shared_event"


class BadClient:
    def send_orphaned(self, comm):
        # sent, but no handler anywhere registers for it
        msg = Message(type=MSG_TYPE_ORPHANED, sender_id=1, receiver_id=0)
        comm.send_message(msg)

    def send_upload(self, comm):
        msg = Message(type=Message.MSG_TYPE_UPLOAD, sender_id=1, receiver_id=0)
        msg.add_params(Message.MSG_ARG_KEY_MODEL_PARAMS, {})
        # raw literal shadowing Message.MSG_ARG_KEY_NUM_SAMPLES
        msg.add_params("num_samples", 10)
        comm.send_message(msg)


class BadServer:
    def register(self):
        self.register_message_receive_handler(
            Message.MSG_TYPE_UPLOAD, self.handle_upload)

    def register_message_receive_handler(self, msg_type, handler):
        pass

    def handle_upload(self, msg):
        params = msg.get(Message.MSG_ARG_KEY_MODEL_PARAMS)
        # no sender of MSG_TYPE_UPLOAD ever stamps this key
        staleness = msg.get("model_version")
        return params, staleness
