"""Fixture: collectives under per-process control flow — every guard
flavour the checker recognises (process_index, *rank*, tenant, ternary)."""

import jax
from jax import lax

from comm_stub import broadcast_one_to_all


def sync_stats(x):
    if jax.process_index() == 0:
        return jax.lax.psum(x, "data")  # only process 0 ever joins
    return x


def rank_guarded(x, rank):
    if rank == 0:
        return lax.all_gather(x, "model")
    return x


def ternary(x):
    return lax.pmean(x, "data") if jax.host_id() == 0 else x


class TenantWorker:
    def maybe_broadcast(self, x):
        if self.tenant == "a":
            return broadcast_one_to_all(x)  # tenants share one mesh
        return x
