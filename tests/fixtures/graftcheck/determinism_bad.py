"""Fixture: nondeterminism — unseeded RNGs, time seeds, set ordering."""

import random
import time

import numpy as np


def make_rng():
    return np.random.default_rng()


def make_py_rng():
    return random.Random()


def time_seeded():
    return np.random.default_rng(int(time.time()))


def reseed_global():
    np.random.seed(0)


def cohort_order(client_ids):
    return list(set(client_ids))


def quantize_without_seed(vals, codec):
    # seed omitted entirely (only vals, bits passed)
    return codec.stochastic_quantize(vals, 8)


def quantize_none_seed(vals, codec):
    return codec.stochastic_quantize(vals, 8, seed=None, round_idx=0,
                                     client_id=0)


def key_time_seed(codec):
    import time

    return codec.stochastic_key(int(time.time()), 0, 0)


def roundtrip_without_seed(spec, codec):
    return codec.build_stacked_roundtrip(spec)


def spec_leaf_order(param_paths):
    # partition-spec inference iterating an unordered set of leaf paths:
    # two runs could assign specs in different orders
    return list(set(param_paths))
