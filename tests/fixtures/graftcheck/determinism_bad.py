"""Fixture: nondeterminism — unseeded RNGs, time seeds, set ordering."""

import random
import time

import numpy as np


def make_rng():
    return np.random.default_rng()


def make_py_rng():
    return random.Random()


def time_seeded():
    return np.random.default_rng(int(time.time()))


def reseed_global():
    np.random.seed(0)


def cohort_order(client_ids):
    return list(set(client_ids))
