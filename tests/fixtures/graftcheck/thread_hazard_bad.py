"""Fixture: cross-thread attribute races (fed to the checker under a
comm/ relpath). A receive-loop thread writes shared state that the main
thread reads with no common lock — including the one-sided-locking trap
where only the reader takes the lock."""

import threading
from concurrent.futures import ThreadPoolExecutor


class Wire:
    def __init__(self):
        self._lock = threading.Lock()
        self.status = None
        self._pending = {}

    def start(self):
        t = threading.Thread(target=self._read_loop, daemon=True)
        t.start()

    def _read_loop(self):
        while True:
            msg = self._recv()
            self.status = msg          # unlocked write from the thread
            self._pending[msg.id] = msg

    def poll(self):
        return self.status             # unlocked read from main

    def flush(self):
        with self._lock:               # reader locks, writer doesn't:
            self._pending.clear()      # still a race

    def _recv(self):
        return None


class Pump:
    def __init__(self):
        self._pool = ThreadPoolExecutor(max_workers=1)
        self.result = None

    def kick(self, work):
        self._pool.submit(self._work, work)

    def _work(self, work):
        self.result = work()           # executor-thread write

    def read(self):
        return self.result             # main-thread read, no lock anywhere
