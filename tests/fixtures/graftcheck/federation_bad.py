"""Fixture: the hazards the tiered-federation modules must not grow
(fed to the checkers under the simulation/federation relpath). A leaf's
heartbeat thread reads the round counter that the receive-loop handlers
write with no common lock, and the root nests its lease-table and
commit-ledger locks in opposite orders on the dispatch and failover
paths — the races/deadlocks thread-hazard and lock-order guard."""

import threading
import time


class BadLeafWorker:
    def __init__(self):
        self._lock = threading.Lock()
        self._round = 0

    def start(self):
        t = threading.Thread(target=self._heartbeat_loop, daemon=True)
        t.start()

    def _heartbeat_loop(self):
        while True:
            self._send_heartbeat(self._round)   # unlocked read from thread

    def on_dispatch(self, msg):
        self._round = msg.round_idx             # unlocked main-thread write

    def _send_heartbeat(self, round_idx):
        return None


class BadRootCoordinator:
    def __init__(self):
        self._lease_lock = threading.Lock()
        self._ledger_lock = threading.Lock()

    def dispatch(self, round_idx):
        with self._lease_lock:
            with self._ledger_lock:
                time.sleep(0.1)                 # blocking under both locks

    def failover(self, dead_rank):
        # opposite nesting order from dispatch() — AB/BA deadlock when a
        # lease expiry races a round dispatch
        with self._ledger_lock:
            with self._lease_lock:
                pass
