"""Fixture package: the other side of the conflicting default, plus
exempt read shapes (None probe, fallback chain)."""


def configure(args):
    retries = int(getattr(args, "retry_count", 3))
    lr = float(getattr(args, "learning_rate", 0.03))
    # None probe: delegates the decision, never conflicts
    probe = getattr(args, "retry_count", None)
    # fallback chain: the inner default belongs to the chain
    window = getattr(args, "retry_window", getattr(args, "retry_count", 9))
    return retries, lr, probe, window
