"""Fixture package: one side of a conflicting-default pair, plus an
undocumented key."""


def configure(args):
    retries = int(getattr(args, "retry_count", 0))
    batch = int(getattr(args, "batch_size", 32))
    lr = float(getattr(args, "learning_rate", 0.03))
    return retries, batch, lr
