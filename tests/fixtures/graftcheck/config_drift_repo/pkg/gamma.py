"""Fixture package: phase emission sites for the phase-name drift rule.

``warp`` is documented in docs/observability.md (clean); ``mystery_phase``
is not (fires ``phase-undocumented:mystery_phase``).
"""

import time


class Sim:
    def __init__(self):
        self._phase_acc = []

    def step(self):
        t = time.perf_counter()
        self._phase_acc.append(("warp", time.perf_counter() - t))
        self._phase_acc.append(("mystery_phase", time.perf_counter() - t))
        # non-tuple / non-constant appends are ignored by the rule
        self._phase_acc.append("not_a_tuple")
        name = "dynamic"
        self._phase_acc.append((name, 0.0))
