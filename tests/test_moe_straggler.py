"""MoE expert parallelism + straggler-tolerant cross-silo rounds."""

import threading

import jax
import jax.numpy as jnp
import numpy as np

import fedml_tpu
from fedml_tpu.ops.moe import MoEBlock, top1_routing


def test_top1_routing_capacity_and_combine():
    logits = jnp.asarray(np.random.default_rng(0).normal(size=(16, 4)), jnp.float32)
    dispatch, combine, aux = top1_routing(logits, num_experts=4, capacity=8)
    assert dispatch.shape == (16, 4, 8)
    # each token dispatched at most once
    assert float(dispatch.sum(axis=(1, 2)).max()) <= 1.0
    # combine weights bounded by gate probabilities
    assert float(combine.max()) <= 1.0
    assert np.isfinite(float(aux))


def test_moe_block_runs_and_shards():
    from jax.sharding import NamedSharding, PartitionSpec as P

    from fedml_tpu.parallel import AXIS_DATA, AXIS_EXPERT, MeshConfig, create_mesh

    from fedml_tpu.ops.moe import expert_param_shardings

    mesh = create_mesh(MeshConfig(axes=((AXIS_DATA, 2), (AXIS_EXPERT, 4))))
    block = MoEBlock(num_experts=4, dim=32, hidden_mult=2)
    x = jnp.asarray(np.random.default_rng(1).normal(size=(4, 8, 32)), jnp.float32)
    params = block.init(jax.random.PRNGKey(0), x)
    params = jax.device_put(params, expert_param_shardings(mesh, params))
    # the helper's contract: expert-stacked kernels sharded, gate replicated
    shardings = expert_param_shardings(mesh, params)
    assert shardings["params"]["w_in"].spec == P(AXIS_EXPERT)
    assert shardings["params"]["gate"]["kernel"].spec == P()
    x_sh = jax.device_put(x, NamedSharding(mesh, P(AXIS_DATA)))
    out, aux = jax.jit(lambda p, x: block.apply(p, x))(params, x_sh)
    assert out.shape == x.shape
    assert np.isfinite(np.asarray(out)).all()
    assert np.isfinite(float(aux))


def test_moe_learns_routing():
    """MoE block trains end-to-end (gradients flow through routing)."""
    import optax

    block = MoEBlock(num_experts=2, dim=8, hidden_mult=2)
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.normal(size=(8, 4, 8)), jnp.float32)
    y = jnp.asarray(np.roll(np.asarray(x), 1, axis=-1))
    params = block.init(jax.random.PRNGKey(0), x)
    opt = optax.adam(1e-2)
    state = opt.init(params)

    @jax.jit
    def step(params, state):
        def loss_fn(p):
            out, aux = block.apply(p, x)
            return jnp.mean(jnp.square(out - y)) + 1e-2 * aux

        loss, grads = jax.value_and_grad(loss_fn)(params)
        upd, state2 = opt.update(grads, state, params)
        return optax.apply_updates(params, upd), state2, loss

    losses = []
    for _ in range(40):
        params, state, loss = step(params, state)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.8


def test_straggler_timeout_closes_round():
    from fedml_tpu.comm import LoopbackHub, Message
    from fedml_tpu.comm.loopback import LoopbackCommManager
    from fedml_tpu.cross_silo import FedML_Horizontal, MyMessage

    args = fedml_tpu.init(config=dict(
        dataset="mnist", model="lr", debug_small_data=True,
        client_num_in_total=2, client_num_per_round=2, comm_round=2,
        learning_rate=0.1, batch_size=8, frequency_of_the_test=1,
        random_seed=0, round_timeout=1.5, min_clients_per_round=1,
    ))
    hub = LoopbackHub()
    server = FedML_Horizontal(args, 0, 2, backend="LOOPBACK", hub=hub)
    good = FedML_Horizontal(args, 1, 2, backend="LOOPBACK", hub=hub)

    class DeadClient:
        """Reports ONLINE then never uploads (a crashed silo)."""

        def __init__(self, rank):
            self.rank = rank
            self.comm = LoopbackCommManager(rank=rank, size=3, hub=hub)
            self.comm.add_observer(self)

        def receive_message(self, t, msg):
            if t == MyMessage.MSG_TYPE_S2C_CHECK_CLIENT_STATUS:
                r = Message(MyMessage.MSG_TYPE_C2S_CLIENT_STATUS, self.rank, 0)
                r.add_params(MyMessage.MSG_ARG_KEY_CLIENT_STATUS,
                             MyMessage.MSG_CLIENT_STATUS_IDLE)
                self.comm.send_message(r)
            elif t == MyMessage.MSG_TYPE_S2C_FINISH:
                self.comm.stop_receive_message()

        def run(self):
            self.comm.handle_receive_message()

    dead = DeadClient(2)
    threads = [
        threading.Thread(target=good.run, daemon=True),
        threading.Thread(target=dead.run, daemon=True),
    ]
    for t in threads:
        t.start()
    server.start()
    server.run()  # must NOT hang despite the dead client
    for t in threads:
        t.join(timeout=30)
    assert len(server.history) == 2
    assert np.isfinite(server.history[-1]["test_acc"])


def test_round_times_out_with_zero_uploads():
    """ADVICE r1: the round timer must arm at round *start* — a round where
    every selected client dies before its first upload still times out
    (min_clients=0 lets it close with the model unchanged)."""
    from fedml_tpu.comm import LoopbackHub, Message
    from fedml_tpu.comm.loopback import LoopbackCommManager
    from fedml_tpu.cross_silo import FedML_Horizontal, MyMessage

    args = fedml_tpu.init(config=dict(
        dataset="mnist", model="lr", debug_small_data=True,
        client_num_in_total=2, client_num_per_round=2, comm_round=2,
        learning_rate=0.1, batch_size=8, frequency_of_the_test=1,
        random_seed=0, round_timeout=1.0, min_clients_per_round=0,
    ))
    hub = LoopbackHub()
    server = FedML_Horizontal(args, 0, 2, backend="LOOPBACK", hub=hub)

    class DeadClient:
        """Reports ONLINE, then never uploads anything."""

        def __init__(self, rank):
            self.rank = rank
            self.comm = LoopbackCommManager(rank=rank, size=3, hub=hub)
            self.comm.add_observer(self)

        def receive_message(self, t, msg):
            if t == MyMessage.MSG_TYPE_S2C_CHECK_CLIENT_STATUS:
                r = Message(MyMessage.MSG_TYPE_C2S_CLIENT_STATUS, self.rank, 0)
                r.add_params(MyMessage.MSG_ARG_KEY_CLIENT_STATUS,
                             MyMessage.MSG_CLIENT_STATUS_IDLE)
                self.comm.send_message(r)
            elif t == MyMessage.MSG_TYPE_S2C_FINISH:
                self.comm.stop_receive_message()

        def run(self):
            self.comm.handle_receive_message()

    dead = [DeadClient(1), DeadClient(2)]
    threads = [threading.Thread(target=d.run, daemon=True) for d in dead]
    for t in threads:
        t.start()
    server.start()
    server.run()  # must NOT hang: timer armed at round start closes rounds
    for t in threads:
        t.join(timeout=30)
    assert len(server.history) == 2


def test_top2_routing_properties():
    from fedml_tpu.ops.moe import top2_routing

    rng = np.random.default_rng(3)
    N, E, C = 64, 4, 40
    logits = jnp.asarray(rng.normal(size=(N, E)), jnp.float32)
    dispatch, combine, aux = top2_routing(logits, num_experts=E, capacity=C)
    assert dispatch.shape == (N, E, C)
    # with ample capacity every token occupies exactly two expert slots
    np.testing.assert_allclose(np.asarray(dispatch.sum(axis=(1, 2))), 2.0)
    # combine gates renormalize over the kept pair -> sum to 1 per token
    np.testing.assert_allclose(
        np.asarray(combine.sum(axis=(1, 2))), 1.0, atol=1e-5)
    # each (expert, slot) queue position holds at most one token
    assert float(dispatch.sum(axis=0).max()) <= 1.0 + 1e-6
    assert np.isfinite(float(aux))


def test_top2_capacity_drops_second_choices_first():
    from fedml_tpu.ops.moe import top2_routing

    # all tokens prefer expert 0 then expert 1: tight capacity keeps
    # expert-0 first choices up to C and drops overflow
    N, E, C = 16, 4, 4
    logits = jnp.tile(jnp.asarray([[5.0, 4.0, 0.0, -5.0]]), (N, 1))
    dispatch, combine, _ = top2_routing(logits, num_experts=E, capacity=C)
    per_expert = np.asarray(dispatch.sum(axis=(0, 2)))
    assert per_expert[0] == C  # expert 0 full with first choices
    assert per_expert[1] == C  # expert 1 full with second choices
    assert per_expert[2] == per_expert[3] == 0


def test_moe_block_top2_learns_routing():
    """Top-2 block trains end-to-end (gradients flow through both ranks'
    dispatch/combine and the aux loss)."""
    rng = np.random.default_rng(5)
    x = jnp.asarray(rng.normal(size=(4, 8, 8)), jnp.float32)
    y = jnp.asarray(rng.normal(size=(4, 8, 8)), jnp.float32)
    block = MoEBlock(num_experts=4, dim=8, hidden_mult=2, top_k=2)
    params = block.init(jax.random.PRNGKey(0), x)

    def loss_fn(p):
        out, aux = block.apply(p, x)
        return jnp.mean((out - y) ** 2) + 1e-2 * aux

    import optax

    opt = optax.adam(1e-2)
    state = opt.init(params)
    losses = []
    for _ in range(60):
        l, g = jax.value_and_grad(loss_fn)(params)
        updates, state = opt.update(g, state, params)
        params = optax.apply_updates(params, updates)
        losses.append(float(l))
    assert losses[-1] < losses[0] * 0.6, (losses[0], losses[-1])


def test_top2_saturated_gates_no_phantom_routing():
    """Saturated logits (softmax underflow) must still pick the true
    second-best expert, not phantom-route to expert 0 (review finding)."""
    from fedml_tpu.ops.moe import top2_routing

    logits = jnp.tile(jnp.asarray([[-100.0, -100.0, 0.0, -99.0]]), (4, 1))
    dispatch, _, _ = top2_routing(logits, num_experts=4, capacity=8)
    per_expert = np.asarray(dispatch.sum(axis=(0, 2)))
    # first choice expert 2, second choice expert 3 — expert 0 untouched
    np.testing.assert_array_equal(per_expert, [0.0, 0.0, 4.0, 4.0])
