"""Unified telemetry: metrics registry, cross-backend trace propagation,
per-round phase attribution, exporters, and the mlops observability fixes."""

import json
import threading
import time

import numpy as np
import pytest

import fedml_tpu
from fedml_tpu.comm import LoopbackHub, Message
from fedml_tpu.comm.loopback import LoopbackCommManager
from fedml_tpu.core import telemetry
from fedml_tpu.core.mlops import (
    MetricsSink,
    MLOpsProfilerEvent,
    MLOpsRuntimeLog,
    SysStats,
)


@pytest.fixture(autouse=True)
def _fresh_telemetry():
    telemetry.configure(enabled=True, reset=True)
    yield
    telemetry.configure(enabled=True, reset=True)


# --- registry ----------------------------------------------------------------


def test_registry_counter_gauge_histogram():
    reg = telemetry.get_registry()
    reg.counter("c", role="server").inc()
    reg.counter("c", role="server").inc(2)
    assert reg.counter("c", role="server").value == 3
    reg.gauge("g").set(7.5)
    assert reg.gauge("g").value == 7.5
    h = reg.histogram("h")
    for v in (0.001, 0.01, 0.1):
        h.observe(v)
    assert h.count == 3
    assert h.sum == pytest.approx(0.111)
    assert 0.0005 <= h.quantile(0.5) <= 0.05


def test_registry_kind_mismatch_raises():
    reg = telemetry.get_registry()
    reg.counter("x")
    with pytest.raises(TypeError):
        reg.gauge("x")


def test_histogram_bucket_merge_across_snapshots():
    """Per-process snapshots must merge: bucket counts/sums add, so a
    multi-host run can aggregate into one registry (ISSUE: mergeable
    across processes)."""
    a = telemetry.MetricsRegistry()
    b = telemetry.MetricsRegistry()
    for reg, vals in ((a, (0.001, 0.02)), (b, (0.001, 0.5, 3.0))):
        h = reg.histogram("lat", phase="agg")
        for v in vals:
            h.observe(v)
    a.counter("n").inc(2)
    b.counter("n").inc(3)
    merged = telemetry.MetricsRegistry()
    merged.merge_snapshot(a.snapshot())
    merged.merge_snapshot(b.snapshot())
    snap = merged.snapshot()
    assert snap["counters"]["n"] == 5
    mh = snap["histograms"]["lat{phase=agg}"]
    assert mh["count"] == 5
    assert mh["sum"] == pytest.approx(3.522)
    # bucket-by-bucket: merged counts are the elementwise sum
    ah = a.snapshot()["histograms"]["lat{phase=agg}"]
    bh = b.snapshot()["histograms"]["lat{phase=agg}"]
    assert mh["counts"] == [x + y for x, y in zip(ah["counts"], bh["counts"])]


def test_histogram_merge_scheme_mismatch_raises():
    a = telemetry.MetricsRegistry()
    a.histogram("h", scheme=telemetry.SECONDS_SCHEME).observe(0.1)
    b = telemetry.MetricsRegistry()
    b.histogram("h", scheme=telemetry.BYTES_SCHEME).observe(100)
    with pytest.raises(ValueError):
        a.merge_snapshot(b.snapshot())


def test_disabled_registry_is_cheap_noop():
    """telemetry_enabled=False must cost ~nothing on hot paths: null
    metrics, no allocation, no span records, unmodified messages."""
    telemetry.configure(enabled=False)
    reg = telemetry.get_registry()
    n = 20_000
    t0 = time.perf_counter()
    for _ in range(n):
        reg.counter("hot").inc()
        reg.histogram("lat").observe(0.1)
    per_op = (time.perf_counter() - t0) / (2 * n)
    assert per_op < 20e-6  # generous CI bound; measured ~0.1 µs
    assert reg.snapshot() == {"counters": {}, "gauges": {}, "histograms": {}}
    with telemetry.get_tracer().span("s") as ctx:
        assert ctx is None
    assert telemetry.get_tracer().finished_spans() == []
    assert telemetry.new_round_context(0) is None
    msg = Message(1, 0, 1)
    before = dict(msg.get_params())
    telemetry.inject_trace(msg)
    assert msg.get_params() == before


# --- trace propagation -------------------------------------------------------


def test_span_exception_path_records_error_status():
    tracer = telemetry.get_tracer()
    with pytest.raises(RuntimeError):
        with tracer.span("will_fail", round_idx=3):
            raise RuntimeError("boom")
    spans = tracer.finished_spans()
    assert len(spans) == 1
    assert spans[0]["status"] == "error"
    assert spans[0]["round_idx"] == 3
    assert telemetry.current_context() is None  # context restored on raise


def test_trace_survives_message_roundtrip():
    ctx = telemetry.new_round_context(11)
    msg = Message(1, 0, 1)
    with telemetry.use_context(ctx):
        telemetry.inject_trace(msg)
    wire = Message.from_bytes(msg.to_bytes())
    got = telemetry.extract_trace(wire)
    assert got is not None
    assert (got.trace_id, got.round_idx) == (ctx.trace_id, 11)


def test_no_context_leaves_message_unstamped():
    """Handshake/status traffic outside any round must stay byte-identical
    to the pre-telemetry wire format."""
    msg = Message(1, 0, 1)
    before = msg.to_bytes()
    telemetry.inject_trace(msg)
    assert msg.to_bytes() == before
    assert telemetry.extract_trace(msg) is None


def _observed_ctx_roundtrip(make_pair, sender_rank=0, receiver_rank=1):
    """Shared harness: send one message under a fresh round context through
    a backend pair; return (sent ctx, ctx observed inside the receiver's
    observer dispatch)."""
    sender, receiver = make_pair()
    seen = []

    class Obs:
        def receive_message(self, t, msg):
            seen.append(telemetry.current_context())
            receiver.stop_receive_message()

    receiver.add_observer(Obs())
    rx = threading.Thread(target=receiver.handle_receive_message, daemon=True)
    rx.start()
    ctx = telemetry.new_round_context(5)
    with telemetry.use_context(ctx):
        msg = Message(1, sender_rank, receiver_rank)
        msg.add_params("w", np.arange(4, dtype=np.float32))
        sender.send_message(msg)
    rx.join(timeout=10)
    assert not rx.is_alive(), "receiver never saw the message"
    assert len(seen) == 1
    return ctx, seen[0]


def _assert_parity(ctx, got):
    assert got is not None, "receiver dispatched without a trace context"
    assert got.trace_id == ctx.trace_id
    assert got.round_idx == 5


def test_trace_propagation_loopback():
    hub = LoopbackHub()

    def make_pair():
        return (LoopbackCommManager(0, 2, hub=hub),
                LoopbackCommManager(1, 2, hub=hub))

    _assert_parity(*_observed_ctx_roundtrip(make_pair))


def test_trace_propagation_grpc():
    pytest.importorskip("grpc")
    from fedml_tpu.comm.grpc_backend import GRPCCommManager

    managers = []

    def make_pair():
        managers.append(GRPCCommManager(rank=0, size=2, base_port=19450))
        managers.append(GRPCCommManager(rank=1, size=2, base_port=19450))
        return managers[0], managers[1]

    try:
        _assert_parity(*_observed_ctx_roundtrip(make_pair))
    finally:
        for m in managers:
            m._server.stop(grace=0)


def test_trace_propagation_mqtt_s3():
    from fedml_tpu.comm.mqtt_s3 import MqttS3CommManager
    from fedml_tpu.comm.pubsub import InProcessBroker
    from fedml_tpu.comm.store import InMemoryBlobStore

    broker, store = InProcessBroker(), InMemoryBlobStore()

    def make_pair():
        server = MqttS3CommManager(broker, store, rank=0, size=2)
        client = MqttS3CommManager(broker, store, rank=1, size=2)
        return server, client

    _assert_parity(*_observed_ctx_roundtrip(make_pair))


def test_trace_propagation_trpc():
    from fedml_tpu.comm.trpc_backend import TRPCCommManager

    managers = []

    def make_pair():
        managers.append(TRPCCommManager(rank=0, size=2, base_port=19470))
        managers.append(TRPCCommManager(rank=1, size=2, base_port=19470))
        return managers[0], managers[1]

    try:
        _assert_parity(*_observed_ctx_roundtrip(make_pair))
    finally:
        for m in managers:
            try:
                m.stop_receive_message()
            except Exception:
                pass


def test_cross_silo_round_trace_parity_and_rtt(monkeypatch):
    """Full loopback deployment: every round's trace_id must be IDENTICAL on
    the server and on every participating client, and the server must have
    recorded per-client round-trip histograms."""
    from fedml_tpu.cross_silo import FedML_Horizontal

    args = fedml_tpu.init(config=dict(
        dataset="mnist", model="lr", debug_small_data=True,
        client_num_in_total=4, client_num_per_round=2, comm_round=3,
        learning_rate=0.1, epochs=1, batch_size=8, frequency_of_the_test=1,
        random_seed=0,
    ))
    telemetry.configure(enabled=True, reset=True)
    hub = LoopbackHub()
    server = FedML_Horizontal(args, 0, 2, backend="LOOPBACK", hub=hub)
    clients = [FedML_Horizontal(args, r, 2, backend="LOOPBACK", hub=hub)
               for r in (1, 2)]
    threads = [threading.Thread(target=c.run, daemon=True) for c in clients]
    for t in threads:
        t.start()
    server.start()
    server.run()
    for t in threads:
        t.join(timeout=60)
    assert len(server.history) == 3
    assert sorted(server.round_trace_ids) == [0, 1, 2]
    for c in clients:
        for r, tid in c.round_trace_ids.items():
            assert tid == server.round_trace_ids[r], (c.rank, r)
    snap = telemetry.get_registry().snapshot()
    rtt = [k for k in snap["histograms"]
           if k.startswith("fedml_client_round_trip_seconds")]
    assert len(rtt) == 2  # one histogram per client rank
    names = {s["name"] for s in telemetry.get_tracer().finished_spans()}
    assert {"client.train", "server.agg_and_eval"} <= names


# --- simulator phase attribution --------------------------------------------


def test_simulator_phase_breakdown_sums_to_round_time():
    args = fedml_tpu.init(config=dict(
        dataset="mnist", model="lr", debug_small_data=True,
        client_num_in_total=8, client_num_per_round=4, comm_round=5,
        learning_rate=0.1, epochs=1, batch_size=8, frequency_of_the_test=2,
        random_seed=0,
    ))
    telemetry.configure(enabled=True, reset=True)
    history = fedml_tpu.run_simulation(args=args)
    assert len(history) == 5
    for rec in history:
        phases = rec["phases"]
        assert set(phases) >= {"device", "host_other"}
        total = sum(phases.values())
        # the accumulator drains at the same stamp round_time is taken, so
        # coverage is exact up to clock jitter (ISSUE bound: within 5%)
        assert total == pytest.approx(rec["round_time"], rel=0.05, abs=2e-4)
    snap = telemetry.get_registry().snapshot()
    assert snap["counters"]["fedml_rounds_total"] == 5
    assert any(k.startswith("fedml_round_phase_seconds") for k in
               snap["histograms"])


# --- exporters ---------------------------------------------------------------


def test_prometheus_textfile_format(tmp_path):
    reg = telemetry.get_registry()
    reg.counter("fedml_rounds_total").inc(3)
    reg.gauge("fedml_cpu_utilization").set(12.5)
    reg.histogram("fedml_round_seconds").observe(0.25)
    path = tmp_path / "metrics.prom"
    telemetry.write_prometheus(str(path))
    text = path.read_text()
    assert "# TYPE fedml_rounds_total counter" in text
    assert "fedml_rounds_total 3" in text
    assert "fedml_cpu_utilization 12.5" in text
    assert "# TYPE fedml_round_seconds histogram" in text
    assert 'fedml_round_seconds_bucket{le="+Inf"} 1' in text
    assert "fedml_round_seconds_count 1" in text
    # cumulative buckets: counts are monotone nondecreasing over edges
    cums = [int(line.rsplit(" ", 1)[1]) for line in text.splitlines()
            if line.startswith("fedml_round_seconds_bucket")]
    assert cums == sorted(cums)


def test_jsonl_sink_and_cli_summary(tmp_path):
    from click.testing import CliRunner

    from fedml_tpu.cli.main import cli

    path = tmp_path / "run.jsonl"
    telemetry.configure(enabled=True, jsonl_path=str(path), reset=True)
    reg = telemetry.get_registry()
    with telemetry.get_tracer().span("server.agg_and_eval", round_idx=0):
        pass
    reg.histogram("fedml_round_phase_seconds", phase="device").observe(0.2)
    reg.counter("fedml_rounds_total").inc()
    telemetry.flush()
    telemetry.configure(enabled=True)  # detach the sink -> closes the file
    kinds = [json.loads(line)["kind"] for line in
             path.read_text().splitlines()]
    assert kinds.count("span") == 1
    assert kinds.count("registry_snapshot") == 1
    result = CliRunner().invoke(cli, ["telemetry", "summary", str(path)])
    assert result.exit_code == 0, result.output
    assert "server.agg_and_eval" in result.output
    assert "fedml_rounds_total = 1" in result.output
    assert "round phase breakdown" in result.output


# --- mlops satellites --------------------------------------------------------


def test_metrics_sink_ring_buffer_drops_oldest():
    sink = MetricsSink(max_records=3)
    for i in range(5):
        sink.emit({"i": i})
    assert len(sink.records) == 3
    assert [r["i"] for r in sink.records] == [2, 3, 4]
    assert sink.dropped_records == 2
    assert sink.records[0]["i"] == 2  # indexing still works (test contract)


def test_runtime_log_rebinds_args_on_every_get_instance():
    class A:
        rank = 0
        run_id = "first"

    class B:
        rank = 3
        run_id = "second"

    inst1 = MLOpsRuntimeLog.get_instance(A())
    inst2 = MLOpsRuntimeLog.get_instance(B())
    assert inst1 is inst2  # still a singleton...
    assert inst2.args.run_id == "second"  # ...but bound to the NEW run


def test_sys_stats_interval_deltas_and_cached_process():
    psutil = pytest.importorskip("psutil")  # noqa: F841
    SysStats._last_counters = None  # isolate from other tests
    s1 = SysStats()
    first = s1.to_dict()
    # first sample has no previous interval: deltas must be 0, not a
    # boot-cumulative lump
    assert first["net_sent_mb"] == 0.0
    assert first["net_recv_mb"] == 0.0
    assert first["interval_s"] == 0.0
    s2 = SysStats()
    assert s2._process is s1._process  # one cached psutil handle per process
    time.sleep(0.05)
    second = SysStats().to_dict()
    assert second["interval_s"] > 0.0
    assert second["net_sent_mb"] >= 0.0
    assert first["host_memory_total_gb"] > 0


def test_profiler_span_emits_ended_event_on_exception():
    sink = MetricsSink()
    ev = MLOpsProfilerEvent(sink=sink)
    with pytest.raises(ValueError):
        with ev.span("agg", event_value="r0"):
            raise ValueError("mid-span failure")
    kinds = [r["kind"] for r in sink.records]
    assert kinds == ["event_started", "event_ended"]
    assert ev._open_events == {}  # no dangling open span


def test_device_trace_start_failure_leaves_no_dangling_span(monkeypatch):
    import jax

    sink = MetricsSink()
    ev = MLOpsProfilerEvent(sink=sink)

    def boom(_dir):
        raise RuntimeError("trace already active")

    monkeypatch.setattr(jax.profiler, "start_trace", boom)
    with pytest.raises(RuntimeError, match="trace already active"):
        with ev.device_trace("/tmp/nowhere"):
            pass
    assert len(sink.records) == 0  # start failed BEFORE the started event
    assert ev._open_events == {}
