"""Cross-framework parity: engine reproduces the reference torch loop.

VERDICT r2 weak #4: "accuracy parity is asserted, not demonstrated". This
test runs scripts/parity_vs_reference.py's harness — the reference FedAvg
semantics (sampling fedavg_api.py:129-143, local SGD trainer
my_model_trainer_classification.py:15, weighted aggregation
fedavg_api.py:156-171) replicated in torch — against the jitted engine on
identical data/init/sampling/permutations, and asserts the per-round loss
curves and final global params agree to f32 tolerance.
"""

import os
import sys

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "scripts"))

from parity_vs_reference import run_parity  # noqa: E402


def test_engine_matches_reference_torch_loop_lr():
    res = run_parity("lr", (32,), 5, sizes=[64, 48, 32, 64],
                     per_round=3, rounds=4, epochs=2, lr=0.1)
    assert res["max_abs_loss_diff"] < 2e-3, res
    assert res["max_abs_param_diff"] < 2e-3, res


def test_engine_matches_reference_torch_loop_cnn():
    res = run_parity("cnn_fedavg", (28, 28, 1), 10, sizes=[32, 32, 48],
                     per_round=2, rounds=3, epochs=1, lr=0.05)
    assert res["max_abs_loss_diff"] < 2e-3, res
    assert res["max_abs_param_diff"] < 2e-3, res
