"""Asynchronous host-side cohort pipeline: prefetch parity + unit tests.

The round loop overlaps round r+1's host packing with round r's device
compute (simulation/prefetch.py). Correctness rests on two claims, both
tested here: ``build_round_inputs`` is a pure function of (seed, round_idx)
— so lookahead packing is BIT-exact, not approximately equal — and the
vectorized packed builder produces byte-identical lane tensors to the
pre-pipeline per-client loop it replaced.
"""

import threading
import time

import numpy as np
import pytest

import jax

import fedml_tpu
from fedml_tpu.simulation import build_simulator
from fedml_tpu.simulation.prefetch import RoundPrefetcher

# keys whose values are wall-clock measurements, not training results
TIMING_KEYS = {"round_time", "dispatch_time", "pack_time", "pack_wait",
               "overlap", "phases"}


def _args(**kw):
    base = dict(
        dataset="cifar10", model="lr", partition_method="hetero",
        partition_alpha=0.3, debug_small_data=True,
        client_num_in_total=12, client_num_per_round=6, comm_round=3,
        learning_rate=0.05, epochs=1, batch_size=16,
        frequency_of_the_test=3, random_seed=0,
    )
    base.update(kw)
    return fedml_tpu.init(config=base)


def _flat(params):
    return np.concatenate(
        [np.asarray(l, np.float64).ravel() for l in jax.tree.leaves(params)])


def _run(prefetch, **kw):
    sim, apply_fn = build_simulator(_args(prefetch=prefetch, **kw))
    assert sim.cfg.prefetch == prefetch
    hist = sim.run(apply_fn, log_fn=None)
    return _flat(sim.params), hist


def _strip_timing(hist):
    return [{k: v for k, v in rec.items() if k not in TIMING_KEYS}
            for rec in hist]


# --- prefetcher unit tests --------------------------------------------------


def test_prefetcher_delivers_in_order():
    with RoundPrefetcher(lambda r: r * 10, range(5), depth=2) as pf:
        assert [pf.get(r) for r in range(5)] == [0, 10, 20, 30, 40]


def test_prefetcher_propagates_worker_exception_on_its_round():
    def build(r):
        if r == 2:
            raise ValueError("boom at round 2")
        return r

    pf = RoundPrefetcher(build, range(4), depth=1)
    assert pf.get(0) == 0
    assert pf.get(1) == 1
    with pytest.raises(ValueError, match="boom at round 2"):
        pf.get(2)
    # the failure closed the pipeline — no zombie thread, no stale queue
    assert pf._closed
    assert not pf._thread.is_alive()


def test_prefetcher_clean_shutdown_with_full_queue():
    # depth-1 queue + an abandoned consumer: close() must unblock the
    # worker (stuck on put) and join it, idempotently
    pf = RoundPrefetcher(lambda r: r, range(100), depth=1)
    assert pf.get(0) == 0
    time.sleep(0.05)  # let the worker fill the queue and block on put
    pf.close()
    pf.close()
    assert not pf._thread.is_alive()
    with pytest.raises(RuntimeError, match="closed"):
        pf.get(1)


def test_prefetcher_pause_guarantees_quiescence():
    in_build = threading.Event()
    release = threading.Event()

    def build(r):
        in_build.set()
        release.wait(timeout=5)
        return r

    pf = RoundPrefetcher(build, range(3), depth=1)
    try:
        assert in_build.wait(timeout=5)  # worker is INSIDE build(0)
        release.set()
        with pf.paused():
            # pause blocked until the in-flight build finished; while
            # paused the worker must not enter the next build
            in_build.clear()
            release.clear()
            assert not in_build.wait(timeout=0.3)
        release.set()
        assert pf.get(0) == 0
        assert pf.get(1) == 1
    finally:
        release.set()
        pf.close()


# --- bit-exact sync-vs-prefetch parity --------------------------------------


@pytest.mark.parametrize("schedule", ["even", "bucketed", "packed"])
def test_prefetch_parity_with_dropout(schedule):
    """Prefetch on vs off: identical params (bit-exact) and identical
    history modulo timing keys, with dropout injection exercising the
    round-indexed drop RNG."""
    kw = dict(cohort_schedule=schedule, client_dropout_rate=0.3)
    f_sync, h_sync = _run(False, **kw)
    f_pre, h_pre = _run(True, **kw)
    np.testing.assert_array_equal(f_sync, f_pre)
    assert _strip_timing(h_sync) == _strip_timing(h_pre)
    # the pipeline actually overlapped: some round's packing was (mostly)
    # hidden behind earlier device work
    assert max(r["overlap"] for r in h_pre) > 0.0
    assert all(r["overlap"] == 0.0 for r in h_sync)


@pytest.mark.slow
def test_prefetch_checkpoint_resume_matches_uninterrupted_sync(tmp_path):
    """Interrupted-at-2 prefetch resume == uninterrupted synchronous run,
    bit-exact (forced sync points at checkpoint rounds keep orbax state
    consistent with the round the loop believes it is on)."""
    kw = dict(cohort_schedule="packed", client_dropout_rate=0.3,
              comm_round=4, frequency_of_the_test=100)
    full, _ = _run(False, **kw)
    ck = str(tmp_path / "ck")
    _run(True, **dict(kw, comm_round=2, checkpoint_dir=ck,
                      checkpoint_frequency=1))
    f_res, h_res = _run(True, **dict(kw, checkpoint_dir=ck,
                                     checkpoint_frequency=1))
    assert h_res[0]["round"] == 2
    np.testing.assert_array_equal(full, f_res)


# --- vectorized packed builder == legacy per-client loop --------------------


@pytest.mark.parametrize("epochs,drop", [(1, 0.0), (2, 0.3)])
def test_packed_builder_matches_legacy_loop(epochs, drop):
    sim, _ = build_simulator(_args(
        cohort_schedule="packed", epochs=epochs, client_dropout_rate=drop))
    assert sim._packed
    from fedml_tpu.simulation.fed_sim import reference_client_sampling

    cfg = sim.cfg
    for r in range(3):
        ci = np.asarray(reference_client_sampling(
            r, cfg.client_num_in_total, cfg.client_num_per_round))
        rng = np.random.default_rng([cfg.seed, r])
        dmask = None
        if drop > 0:
            dmask = rng.random(len(ci)) < drop
            if dmask.all():
                dmask[0] = False
        new = sim._build_packed_inputs(ci, r, dmask)
        old = sim._build_packed_inputs_loop(ci, r, dmask)
        for k in ("idx", "mask", "boundary", "bweight", "pos", "sic"):
            np.testing.assert_array_equal(
                np.asarray(new[k]), np.asarray(old[k]), err_msg=f"r={r} {k}")
        assert new["shape"] == old["shape"]
        assert new["cohort_n"] == old["cohort_n"]


def test_packed_lane_plan_cache_reused():
    sim, _ = build_simulator(_args(
        cohort_schedule="packed", client_num_per_round=12))
    ci = np.arange(12)
    sim._build_packed_inputs(ci, 0, None)
    assert len(sim._lane_plan_cache) == 1
    plan = next(iter(sim._lane_plan_cache.values()))
    sim._build_packed_inputs(ci, 1, None)
    assert next(iter(sim._lane_plan_cache.values())) is plan
    # a different drop pattern is a different plan
    d = np.zeros(12, bool)
    d[3] = True
    sim._build_packed_inputs(ci, 2, d)
    assert len(sim._lane_plan_cache) == 2


# --- pack_client_index vectorization keeps rng/perm semantics ---------------


def test_pack_client_index_rng_and_perm_paths_consistent():
    sim, _ = build_simulator(_args())
    fed, bs = sim.fed, 4
    ids = list(fed.train_data_local_dict.keys())[:5]
    # the rng path must consume one permutation per client IN COHORT ORDER
    # (bit-compat with the pre-vectorization loop)
    r1 = fed.pack_client_index(ids, bs, 3, rng=np.random.default_rng(7))
    rng = np.random.default_rng(7)
    perms = [rng.permutation(len(fed._global_index[c])) for c in ids]
    r2 = fed.pack_client_index(ids, bs, 3, perms=perms)
    np.testing.assert_array_equal(r1.idx, r2.idx)
    np.testing.assert_array_equal(r1.mask, r2.mask)
    np.testing.assert_array_equal(r1.num_samples, r2.num_samples)
    # no-shuffle path: rows are each client's index list, in order, padded
    r3 = fed.pack_client_index(ids[:1], bs, None)
    n = min(len(fed._global_index[ids[0]]), r3.idx.size)
    np.testing.assert_array_equal(
        r3.idx.ravel()[:n], fed._global_index[ids[0]][:n])


# --- profiler spans ---------------------------------------------------------


def test_profiler_emits_pack_and_dispatch_spans():
    from fedml_tpu.core.mlops import MetricsSink, MLOpsProfilerEvent

    events = []
    sink = MetricsSink()
    sink.emit = events.append
    prof = MLOpsProfilerEvent(sink=sink)
    args = _args(cohort_schedule="even", comm_round=2)
    args.profiler = prof
    sim, _ = build_simulator(args)
    assert sim._profiler is prof
    sim.run(apply_fn=None, log_fn=None)
    by_event = {}
    for e in events:
        by_event.setdefault((e["event"], e["kind"]), []).append(e)
    assert len(by_event[("host_pack", "event_started")]) == 2
    assert len(by_event[("host_pack", "event_ended")]) == 2
    assert len(by_event[("round_dispatch", "event_ended")]) == 2
    # ended spans carry a measured duration
    assert all(e["duration"] is not None
               for e in by_event[("host_pack", "event_ended")])
