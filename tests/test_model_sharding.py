"""2-D federated mesh: model-axis sharding of params, opt-state, aggregation.

The simulator's mesh is promoted from 1-D (``client``) to 2-D (``client`` ×
``model``): per-leaf PartitionSpecs are inferred by the shared
largest-divisible-dim rule (parallel/sharding.py:auto_partition_specs), and
the persistent round state — global params, server opt-state, stacked
per-client rows, EF residuals, the cohort update stack, and the aggregate —
lives on the model axis end-to-end. Local training consumes a TRANSIENT
replicated view (Xu et al., arXiv:2004.13336 lazy weight gather) behind an
explicit propagation barrier, so every claim here is a parity claim: the
round history and final params are BIT-IDENTICAL to the 1-D mesh and the
unsharded path, while placement probes prove the persistent chain never
materializes unsharded.
"""

import warnings

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

import fedml_tpu
from fedml_tpu.parallel.mesh import AXIS_CLIENT, AXIS_MODEL, MeshConfig, create_mesh
from fedml_tpu.parallel.sharding import auto_partition_specs, shard_along
from fedml_tpu.simulation import build_simulator

TIMING_KEYS = {"round_time", "dispatch_time", "pack_time", "pack_wait",
               "overlap", "phases"}


def _args(**kw):
    base = dict(
        dataset="mnist", model="lr", debug_small_data=True,
        client_num_in_total=12, client_num_per_round=4, comm_round=3,
        learning_rate=0.1, epochs=1, batch_size=32,
        frequency_of_the_test=2, random_seed=0,
        partition_method="hetero", partition_alpha=0.5,
        federated_optimizer="SCAFFOLD",
    )
    base.update(kw)
    return fedml_tpu.init(config=base)


def _run(mesh=None, **kw):
    sim, apply_fn = build_simulator(_args(**kw), mesh=mesh)
    hist = sim.run(apply_fn, log_fn=None)
    return sim, hist


def _strip_timing(hist):
    return [{k: v for k, v in rec.items() if k not in TIMING_KEYS}
            for rec in hist]


def _assert_tree_equal(a, b):
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def _mesh1():
    return create_mesh(MeshConfig(axes=((AXIS_CLIENT, 2),)),
                       devices=jax.devices()[:2])


def _mesh2x2():
    return create_mesh(
        MeshConfig(axes=((AXIS_CLIENT, 2), (AXIS_MODEL, 2))),
        devices=jax.devices()[:4])


def _mesh2x4():
    return create_mesh(
        MeshConfig(axes=((AXIS_CLIENT, 2), (AXIS_MODEL, 4))),
        devices=jax.devices()[:8])


# --- spec inference: the largest-divisible-dim rule -------------------------


def test_shard_along_validates_axis_and_dim():
    mesh = _mesh1()
    sh = shard_along(mesh, AXIS_CLIENT, 0)
    assert sh.spec == P(AXIS_CLIENT)
    with pytest.raises(ValueError, match="no axis"):
        shard_along(mesh, "tensor", 0)
    with pytest.raises(ValueError, match="non-negative int"):
        shard_along(mesh, AXIS_CLIENT, -1)
    with pytest.raises(ValueError, match="non-negative int"):
        shard_along(mesh, AXIS_CLIENT, "0")


def test_auto_specs_largest_divisible_dim():
    tree = {
        "kernel": jnp.zeros((784, 10)),   # both divisible; 784 is largest
        "bias": jnp.zeros((10,)),         # divisible -> sharded
        "tall": jnp.zeros((6, 8)),        # 8 > 6 -> dim 1
        "tie": jnp.zeros((4, 4)),         # tie -> lowest dim index
        "scalar": jnp.zeros(()),          # no dims -> replicated
    }
    specs = auto_partition_specs(tree, "model", 2, warn=False)
    assert specs["kernel"] == P("model")
    assert specs["bias"] == P("model")
    assert specs["tall"] == P(None, "model")
    assert specs["tie"] == P("model")
    assert specs["scalar"] == P()


def test_auto_specs_accepts_shape_structs():
    # the simulator infers update-stack specs at trace time from
    # ShapeDtypeStructs — np.shape would choke on them
    tree = {"w": jax.ShapeDtypeStruct((16, 6), jnp.float32)}
    specs = auto_partition_specs(tree, "model", 4, warn=False)
    assert specs["w"] == P("model")


def test_auto_specs_single_warning_lists_all_fallbacks():
    tree = {"a": jnp.zeros((7,)), "b": jnp.zeros((10, 3)), "c": jnp.zeros((8,))}
    with pytest.warns(UserWarning) as rec:
        specs = auto_partition_specs(tree, "model", 4)
    ours = [w for w in rec if "auto_partition_specs" in str(w.message)]
    assert len(ours) == 1
    msg = str(ours[0].message)
    assert "'a'" in msg and "'b'" in msg
    assert specs["a"] == P() and specs["b"] == P()
    assert specs["c"] == P("model")
    # axis size 1: nothing shards, and nothing warns
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        flat = auto_partition_specs(tree, "model", 1)
    assert all(s == P() for s in jax.tree.leaves(
        flat, is_leaf=lambda x: isinstance(x, P)))


def test_auto_specs_overrides():
    tree = {"kernel": jnp.zeros((784, 10)), "bias": jnp.zeros((10,))}
    specs = auto_partition_specs(
        tree, "model", 2, overrides={"kernel": 1, "bias": None}, warn=False)
    assert specs["kernel"] == P(None, "model")
    assert specs["bias"] == P()
    with pytest.raises(ValueError, match="names dim"):
        auto_partition_specs(tree, "model", 2, overrides={"bias": 3})
    with pytest.raises(ValueError, match="not divisible"):
        auto_partition_specs(tree, "model", 4, overrides={"bias": 0})


def test_auto_specs_deterministic():
    tree = {"z": jnp.zeros((8, 4)), "a": jnp.zeros((4, 8)),
            "m": {"x": jnp.zeros((2, 2))}}
    s1 = auto_partition_specs(tree, "model", 2, warn=False)
    s2 = auto_partition_specs(tree, "model", 2, warn=False)
    assert jax.tree.structure(s1, is_leaf=lambda x: isinstance(x, P)) \
        == jax.tree.structure(s2, is_leaf=lambda x: isinstance(x, P))
    assert jax.tree.leaves(s1, is_leaf=lambda x: isinstance(x, P)) \
        == jax.tree.leaves(s2, is_leaf=lambda x: isinstance(x, P))


# --- bit-identity: 2-D mesh vs 1-D mesh vs unsharded ------------------------


def test_2d_mesh_history_bit_identical():
    """The whole point of the lazy-gather design: model-axis sharding is a
    LAYOUT change, not a numerics change. History and final params from the
    2×2 mesh match the 1-D mesh and the unsharded path bit-for-bit, with
    the stateful SCAFFOLD algorithm (server c + per-client c_local rows all
    live on the model axis)."""
    sim0, h0 = _run()
    sim1, h1 = _run(mesh=_mesh1())
    sim2, h2 = _run(mesh=_mesh2x2())
    assert _strip_timing(h0) == _strip_timing(h1) == _strip_timing(h2)
    # param BITS are compared mesh-to-mesh: the unsharded path computes the
    # client reduction unsplit, so (as with the seed's 1-D guarantee) its
    # parity claim is the round history; the model axis itself must not
    # perturb a single bit
    _assert_tree_equal(sim1.params, sim2.params)
    _assert_tree_equal(sim1.server_state, sim2.server_state)
    # and the 2-D run really engaged the model axis
    assert sim2._model_axis == AXIS_MODEL
    assert sim1._model_axis is None


def test_2d_mesh_codec_ef_bit_identical():
    """EF residual arena rows carry cohort×model; the codec roundtrip is
    elementwise + exact top-k selection, so the lossy-wire history is still
    bit-identical between the 1-D and 2-D meshes."""
    common = dict(federated_optimizer="FedAvg",
                  comm_codec="delta|topk:0.25|q8")
    sim1, h1 = _run(mesh=_mesh1(), **common)
    sim2, h2 = _run(mesh=_mesh2x2(), **common)
    assert _strip_timing(h1) == _strip_timing(h2)
    _assert_tree_equal(sim1.params, sim2.params)
    assert sim2._codec_arena is not None
    for leaf in sim2._codec_arena._leaves:
        assert _spec_has_axis(leaf.sharding.spec, AXIS_MODEL)


# --- placement probes: the persistent chain never materializes unsharded ---


def _spec_has_axis(spec, axis):
    flat = []
    for part in spec:
        if isinstance(part, tuple):
            flat.extend(part)
        else:
            flat.append(part)
    return axis in flat


def test_2d_mesh_placement_probes():
    mesh = _mesh2x2()
    args = _args(comm_round=2)
    sim, apply_fn = build_simulator(args, mesh=mesh)
    seen = {}
    sim._sharding_probe = lambda tag, s: seen.setdefault(tag, s)
    sim.run(apply_fn, log_fn=None)
    # in-program probes (inspect_array_sharding reports the compiler's
    # positional form — compare semantically against the expected named
    # layout): the sharded donated jit keeps params in/out, the stacked
    # update, the aggregate, and the server opt-state on the model axis —
    # nothing in the persistent chain is ever fully replicated. Probes fire
    # on the largest leaf: the lr kernel (784, 10) -> P('model'), its
    # stacked cohort form (4, 784, 10) -> P('client', 'model').
    expect = {
        "params_in": NamedSharding(mesh, P(AXIS_MODEL)),
        "update": NamedSharding(mesh, P(AXIS_CLIENT, AXIS_MODEL)),
        "agg": NamedSharding(mesh, P(AXIS_MODEL)),
        "params_out": NamedSharding(mesh, P(AXIS_MODEL)),
        "opt_state_out": NamedSharding(mesh, P(AXIS_MODEL)),
    }
    ndim = {"update": 3}
    for tag, want in expect.items():
        assert tag in seen, f"probe {tag!r} never fired (tags: {sorted(seen)})"
        got = seen[tag]
        assert not got.is_fully_replicated, tag
        assert got.is_equivalent_to(want, ndim.get(tag, 2)), (tag, got)
    # at-rest placement between rounds matches the probes
    for tree in (sim.params, sim.server_state):
        big = max(jax.tree.leaves(tree),
                  key=lambda l: int(np.prod(l.shape)))
        assert _spec_has_axis(big.sharding.spec, AXIS_MODEL)
    # per-client arena rows: cohort axis on dim 0, model axis on the rows
    big = max(sim._arena._leaves, key=lambda l: int(np.prod(l.shape)))
    assert _spec_has_axis(big.sharding.spec, AXIS_MODEL)
    assert _spec_has_axis(big.sharding.spec, AXIS_CLIENT)


# --- sharded checkpoint: interrupt/resume stays bit-exact -------------------


def test_sharded_checkpoint_resume_parity(tmp_path):
    mesh = _mesh2x2()
    ck = dict(checkpoint_dir=str(tmp_path / "ck"), checkpoint_frequency=2,
              comm_round=4)
    sim_full, h_full = _run(mesh=_mesh2x2(), comm_round=4)
    # interrupted run: stop after round 1 (checkpoint fires at idx 1) ...
    _run(mesh=mesh, **{**ck, "comm_round": 2})
    # ... then a FRESH simulator resumes rounds 2-3 from the sharded
    # checkpoint; restore re-places host arrays under the sim's shardings
    sim_res, h_res = _run(mesh=_mesh2x2(), **ck)
    assert [r["round"] for r in h_res] == [2, 3]
    assert _strip_timing(h_res) == _strip_timing(h_full)[2:]
    _assert_tree_equal(sim_res.params, sim_full.params)
    _assert_tree_equal(sim_res.server_state, sim_full.server_state)
    big = max(jax.tree.leaves(sim_res.params),
              key=lambda l: int(np.prod(l.shape)))
    assert _spec_has_axis(big.sharding.spec, AXIS_MODEL)


# --- indivisible leaves: one warning, replicated fallback, same numerics ----


def test_indivisible_leaf_warns_once_and_stays_exact():
    """model axis 4: the lr bias (10,) has no divisible dim -> replicated
    fallback, announced by exactly ONE UserWarning naming the path; the
    kernel (784, 10) still shards (784 % 4 == 0) and the history stays
    bit-identical to the unsharded run."""
    sim1, h1 = _run(mesh=_mesh1())
    with pytest.warns(UserWarning) as rec:
        sim4, h4 = _run(mesh=_mesh2x4())
    ours = [w for w in rec if "auto_partition_specs" in str(w.message)]
    assert len(ours) == 1
    assert "bias" in str(ours[0].message)
    assert _strip_timing(h1) == _strip_timing(h4)
    _assert_tree_equal(sim1.params, sim4.params)
    leaves = {l.shape: l for l in jax.tree.leaves(sim4.params)}
    assert _spec_has_axis(leaves[(784, 10)].sharding.spec, AXIS_MODEL)
    assert leaves[(10,)].sharding.spec == P()


def test_reshard_phase_and_hbm_gauge(monkeypatch):
    """The 2-D path adds a 'reshard' phase (cohort device_put + eval params
    gather) without breaking the invariant that named phases + host_other
    sum exactly to round_time; the per-device HBM peak gauge is set when the
    backend reports memory_stats and silently absent when it doesn't (CPU
    returns None — the gauge loop must not crash on it)."""
    from fedml_tpu.core import telemetry
    telemetry.configure(enabled=True, reset=True)
    try:
        _, hist = _run(mesh=_mesh2x2())
    finally:
        snap = telemetry.get_registry().snapshot()
        telemetry.configure(enabled=False, reset=True)
    # the final round's record finalizes after the loop (deferred readback)
    # and may carry only drain-time phases — seed behavior; the reshard
    # stamps must show up across the run and NEVER break the sum invariant
    assert any("reshard" in rec["phases"] for rec in hist)
    for rec in hist:
        assert sum(rec["phases"].values()) == pytest.approx(
            rec["round_time"], rel=0.05, abs=2e-4)
    has_stats = any((jax.devices()[0].memory_stats() or {})
                    .get("peak_bytes_in_use") is not None for _ in (0,))
    gauges = [k for k in snap["gauges"]
              if k.startswith("fedml_device_hbm_peak_bytes")]
    assert bool(gauges) == has_stats


def test_model_shard_axis_off_disables_sharding():
    # "none" pins everything to the 1-D behavior even on a 2-D mesh
    sim, _ = _run(mesh=_mesh2x2(), comm_round=1, model_shard_axis="none")
    assert sim._model_axis is None
    for leaf in jax.tree.leaves(sim.params):
        assert not _spec_has_axis(leaf.sharding.spec, AXIS_MODEL)
