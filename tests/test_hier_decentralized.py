"""Hierarchical FL, decentralized DSGD/PushSum, FedAvg_robust end-to-end."""

import numpy as np

import fedml_tpu
from fedml_tpu.simulation import build_simulator


def _args(**kw):
    base = dict(
        dataset="mnist", model="lr", debug_small_data=True,
        client_num_in_total=8, client_num_per_round=8, comm_round=3,
        learning_rate=0.1, epochs=1, batch_size=8, frequency_of_the_test=2,
        random_seed=0,
    )
    base.update(kw)
    return fedml_tpu.init(config=base)


def test_hierarchical_fl_learns():
    args = _args(federated_optimizer="HierarchicalFL", group_num=2,
                 group_comm_round=2, comm_round=4)
    sim, apply_fn = build_simulator(args)
    hist = sim.run(apply_fn, log_fn=None)
    assert hist[0]["train_loss"] > hist[-1]["train_loss"]
    assert hist[-1]["test_acc"] > 0.5


def test_decentralized_dsgd_consensus_and_learning():
    args = _args(federated_optimizer="Decentralized", comm_round=5)
    sim, apply_fn = build_simulator(args)
    hist = sim.run(apply_fn, log_fn=None)
    assert hist[0]["train_loss"] > hist[-1]["train_loss"]
    # gossip keeps models near consensus
    assert hist[-1]["consensus_dist"] < 10.0
    assert hist[-1]["test_acc"] > 0.4


def test_decentralized_pushsum_runs():
    args = _args(federated_optimizer="Decentralized", decentralized_mode="pushsum",
                 comm_round=4)
    sim, apply_fn = build_simulator(args)
    hist = sim.run(apply_fn, log_fn=None)
    assert np.isfinite(hist[-1]["train_loss"])
    assert hist[0]["train_loss"] > hist[-1]["train_loss"]


def test_fedavg_robust_clipping_learns():
    args = _args(federated_optimizer="FedAvg_robust",
                 defense_type="norm_diff_clipping", norm_bound=1.0, comm_round=4)
    sim, apply_fn = build_simulator(args)
    hist = sim.run(apply_fn, log_fn=None)
    assert hist[0]["train_loss"] > hist[-1]["train_loss"]


def test_fedavg_robust_median_learns():
    args = _args(federated_optimizer="FedAvg_robust",
                 defense_type="coordinate_median", comm_round=4)
    sim, apply_fn = build_simulator(args)
    hist = sim.run(apply_fn, log_fn=None)
    assert hist[0]["train_loss"] > hist[-1]["train_loss"]


def test_fedavg_robust_weak_dp_fresh_noise():
    args = _args(federated_optimizer="FedAvg_robust", defense_type="weak_dp",
                 norm_bound=5.0, stddev=1e-4, comm_round=3)
    sim, apply_fn = build_simulator(args)
    hist = sim.run(apply_fn, log_fn=None)
    assert np.isfinite(hist[-1]["train_loss"])
