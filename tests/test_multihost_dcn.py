"""Hierarchical FL with the GLOBAL aggregation over the DCN axis: two OS
processes joined by jax.distributed, each training one group locally, the
groups' weighted mean computed as a cross-process mesh collective
(VERDICT r4 #7; reference cross_silo/hierarchical/
dist_trainer_launcher.py:23 torchrun world -> jax.distributed).

Complements tests/test_multiprocess_silo.py (which shards one silo's
batch axis across processes): here the processes hold DIFFERENT models
and the collective performs the cross-silo aggregation itself.
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO_ROOT, "scripts", "run_dcn_hier_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.slow
def test_hierarchical_round_over_dcn(tmp_path):
    port = _free_port()
    outs = [str(tmp_path / f"out_{i}.json") for i in range(2)]
    procs = []
    for pid in range(2):
        env = dict(
            os.environ,
            PYTHONPATH=REPO_ROOT,
            JAX_PLATFORMS="cpu",
            PALLAS_AXON_POOL_IPS="",
            XLA_FLAGS=(os.environ.get("XLA_FLAGS", "")
                       + " --xla_force_host_platform_device_count=2").strip(),
            JAX_COORDINATOR_ADDRESS=f"127.0.0.1:{port}",
            JAX_NUM_PROCESSES="2",
            JAX_PROCESS_ID=str(pid),
        )
        procs.append(subprocess.Popen(
            [sys.executable, WORKER, "--out", outs[pid], "--group-rounds", "2"],
            env=env, cwd=REPO_ROOT,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        ))
    logs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=420)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        logs.append(out)
    assert all(p.returncode == 0 for p in procs), "\n----\n".join(logs)

    r0, r1 = (json.load(open(o)) for o in outs)
    # both processes saw the full 4-device world
    assert r0["global_devices"] == 4 and r0["local_devices"] == 2
    assert r1["global_devices"] == 4 and r1["local_devices"] == 2
    # the groups trained DIFFERENT models (different data + init)...
    assert r0["group_vec_l2"] != pytest.approx(r1["group_vec_l2"])
    # ...yet the cross-process collective left both with the IDENTICAL
    # global model (the DCN reduction actually synchronized them)
    assert r0["merged_digest"] == pytest.approx(r1["merged_digest"], rel=1e-6)
    np.testing.assert_allclose(r0["merged_first8"], r1["merged_first8"],
                               rtol=1e-6)
    # and the merged model evaluates sanely on both groups' test splits
    assert np.isfinite(r0["test_acc"]) and np.isfinite(r1["test_acc"])
    assert r0["test_acc"] > 0.25 and r1["test_acc"] > 0.25
