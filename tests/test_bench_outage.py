"""bench.py outage contract: the driver artifact must ALWAYS parse.

Round 4 lost its only trusted perf number because one transient tunnel
outage left BENCH_r04.json as bare rc=1 with a traceback tail. The
hardened bench must print exactly one JSON line with an "error" field on
any failure path (backend unavailable, bench crash, unreadable
baseline)."""

import io
import json
import sys
from contextlib import redirect_stdout

import bench


def _run_main(monkeypatch, **patches):
    for name, val in patches.items():
        monkeypatch.setattr(bench, name, val, raising=True)
    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = bench.main()
    lines = [ln for ln in buf.getvalue().splitlines() if ln.strip()]
    assert len(lines) == 1, f"expected exactly one stdout line: {lines}"
    return rc, json.loads(lines[0])


def _unavailable(monkeypatch):
    import fedml_tpu.utils.chip_probe as cp

    monkeypatch.setattr(
        cp, "wait_for_chip",
        lambda *a, **k: (False, "probe hung >240s (backend init stuck)"))


def test_backend_unavailable_emits_error_json(monkeypatch):
    _unavailable(monkeypatch)
    rc, rec = _run_main(monkeypatch)
    assert rc == 1
    assert rec["metric"] == "fedavg_cifar10_resnet56_rounds_per_sec"
    assert rec["value"] is None and rec["vs_baseline"] is None
    assert "unavailable" in rec["error"]
    assert "probe hung" in rec["error"]


def test_bench_crash_emits_error_json(monkeypatch):
    import fedml_tpu.utils.chip_probe as cp

    monkeypatch.setattr(cp, "wait_for_chip", lambda *a, **k: (True, "ok"))

    def boom():
        raise RuntimeError("mid-bench tunnel drop")

    rc, rec = _run_main(monkeypatch, run_bench=boom)
    assert rc == 1
    assert rec["value"] is None
    assert "RuntimeError: mid-bench tunnel drop" in rec["error"]


def test_success_emits_value(monkeypatch):
    import fedml_tpu.utils.chip_probe as cp

    monkeypatch.setattr(cp, "wait_for_chip", lambda *a, **k: (True, "ok"))
    rc, rec = _run_main(
        monkeypatch,
        run_bench=lambda: (6.25, {}, {"overlap_mean": 0.8}, {}))
    assert rc == 0
    assert rec["value"] == 6.25
    assert "error" not in rec and "candidate_errors" not in rec
    assert rec["vs_baseline"] > 0
    assert rec["host_pack"] == {"overlap_mean": 0.8}


def test_degraded_ab_run_is_flagged(monkeypatch):
    """A one-executor run (the other carry candidate crashed) must carry
    candidate_errors in the JSON — it is a measurement, but not a clean
    A/B, and automation needs to tell them apart."""
    import fedml_tpu.utils.chip_probe as cp

    monkeypatch.setattr(cp, "wait_for_chip", lambda *a, **k: (True, "ok"))
    rc, rec = _run_main(
        monkeypatch,
        run_bench=lambda: (4.5, {True: "RuntimeError: flat compile blew up"},
                           {}, {}))
    assert rc == 0
    assert rec["value"] == 4.5
    assert rec["candidate_errors"] == {
        "flat": "RuntimeError: flat compile blew up"}


def test_unreadable_baseline_still_emits(monkeypatch):
    _unavailable(monkeypatch)
    monkeypatch.setattr(
        bench, "load_baseline",
        lambda: (_ for _ in ()).throw(ValueError("corrupt json")))
    rc, rec = _run_main(monkeypatch)
    assert rc == 1
    assert "undocumented-1.0" in rec["unit"]


def test_cpu_fallback_counts_as_unavailable(monkeypatch):
    """probe_once must report a cpu-fallback success as failure — the
    bench must never silently measure CPU (review contract). The probe
    subprocess is faked to echo a cpu-platform result."""
    import subprocess
    import sys as _sys

    from fedml_tpu.utils import chip_probe

    real_run = subprocess.run

    def forced_cpu(cmd, **kw):
        return real_run([_sys.executable, "-c",
                         "print('CHIP_PROBE cpu 42.0')"],
                        capture_output=True, text=True)

    monkeypatch.setattr(chip_probe.subprocess, "run", forced_cpu)
    ok, detail = chip_probe.probe_once(timeout=30)
    assert not ok and "cpu" in detail
