"""Centralized (non-federated) baseline trainer — reference
``centralized/centralized_trainer.py:9`` parity."""

import numpy as np

import fedml_tpu


def test_centralized_trainer_learns():
    args = fedml_tpu.init(config=dict(
        dataset="mnist", model="lr", debug_small_data=True,
        epochs=6, learning_rate=0.1, batch_size=32, random_seed=0))
    hist = fedml_tpu.run_centralized(args)
    assert len(hist) == 6  # one record per centralized epoch
    assert all("test_acc" in h for h in hist)  # per-epoch eval cadence
    assert hist[-1]["test_acc"] > 0.85, hist[-1]
    assert hist[-1]["train_loss"] < hist[0]["train_loss"]


def test_centralized_trainer_single_client_data():
    from fedml_tpu import data as data_mod
    from fedml_tpu.centralized import CentralizedTrainer

    args = fedml_tpu.init(config=dict(
        dataset="mnist", model="lr", debug_small_data=True,
        epochs=2, learning_rate=0.1, batch_size=32, random_seed=0))
    trainer = CentralizedTrainer(args=args)
    fed = trainer.sim.fed
    assert fed.client_num == 1  # everything on one client
    total = sum(len(v) for v in fed.train_data_local_dict.values())
    assert total == len(fed.train_data_global.x)
    hist = trainer.train()
    assert np.isfinite(hist[-1]["train_loss"])
