"""Docs-tree integrity: links resolve, every example is reachable from a
doc page, and the generated config reference is not stale."""

import os
import re
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DOCS = os.path.join(REPO, "docs")

LINK_RE = re.compile(r"\[[^\]]*\]\(([^)]+)\)")


def _doc_files():
    return [os.path.join(DOCS, f) for f in sorted(os.listdir(DOCS))
            if f.endswith(".md")]


def test_docs_exist():
    names = {os.path.basename(p) for p in _doc_files()}
    for required in ("README.md", "quickstart_simulation.md",
                     "quickstart_cross_silo.md", "quickstart_cross_device.md",
                     "quickstart_distributed_training.md",
                     "config_reference.md", "performance.md", "apps.md"):
        assert required in names, f"docs/{required} missing"


def test_all_relative_links_resolve():
    broken = []
    for path in _doc_files():
        base = os.path.dirname(path)
        for m in LINK_RE.finditer(open(path).read()):
            target = m.group(1).split("#")[0]  # drop anchors, keep the path
            if not target or target.startswith(
                    ("http://", "https://", "mailto:")):
                continue
            if not os.path.exists(os.path.normpath(os.path.join(base, target))):
                broken.append(f"{os.path.basename(path)} -> {target}")
    assert not broken, broken


def test_every_example_reachable_from_docs():
    examples = {
        d for d in os.listdir(os.path.join(REPO, "examples"))
        if os.path.isdir(os.path.join(REPO, "examples", d))
    }
    corpus = "".join(open(p).read() for p in _doc_files())
    # examples/README.md is itself linked from docs; any example named
    # there counts as reachable too
    corpus += open(os.path.join(REPO, "examples", "README.md")).read()
    missing = [e for e in sorted(examples) if e not in corpus]
    assert not missing, f"examples unreachable from docs: {missing}"


def test_config_reference_not_stale():
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts",
                                      "gen_config_reference.py"), "--check"],
        capture_output=True, text=True, cwd=REPO)
    assert r.returncode == 0, r.stderr or r.stdout
