"""Canary-gated serving plane: versioned store lifecycle (publish /
promote / rollback / pin), trim-boundary retention under reader leases,
seeded canary verdicts, inline and worker-mode gating, the zero-drop
hot-swap guarantee under mixed train/serve load, the poisoned-rollout
drill, and the disabled-path byte-identity pin."""

import time

import numpy as np
import pytest

import fedml_tpu
from fedml_tpu.core import telemetry
from fedml_tpu.serving import (
    CanaryConfig,
    CanaryEvaluator,
    InferenceServer,
    ServeConfig,
    VersionedModelStore,
    build_inference_server,
    held_out_batches,
)


@pytest.fixture(autouse=True)
def _fresh_telemetry():
    telemetry.configure(enabled=True, reset=True)
    yield
    telemetry.configure(enabled=True, reset=True)


def _counters():
    return telemetry.get_registry().snapshot()["counters"]


def _params(v: float, dim: int = 8, classes: int = 4):
    rng = np.random.default_rng(7)
    w = rng.normal(size=(dim, classes)).astype(np.float32)
    return {"w": w * np.float32(v)}


# ----------------------------------------------------------------- store


def test_store_publish_promote_rollback_lifecycle():
    store = VersionedModelStore(keep_versions=8)
    # very first version has nothing to canary against: promoted on landing
    assert store.publish(1, _params(1.0)) == "promoted"
    assert store.active()[0] == 1
    # later versions land as candidates; only promote() swaps the pointer
    assert store.publish(2, _params(1.1)) == "candidate"
    assert store.active()[0] == 1
    assert store.candidate()[0] == 2
    assert store.promote(2)
    assert store.active()[0] == 2
    # a rollback of the live version falls back to the newest promoted one
    assert store.publish(3, _params(-1.0)) == "candidate"
    assert store.promote(3)
    assert store.rollback(3, reason="canary_regression") == 2
    assert store.active()[0] == 2
    assert store.stats()["last_good"] == 2
    # the pin: a rolled-back version number is refused forever
    assert store.publish(3, _params(1.0)) == "pinned"
    # a decided (promoted) version cannot be re-published either
    assert store.publish(2, _params(9.0)) == "duplicate"
    assert store.versions()[3] == "rolled_back"


def test_store_trim_boundary_reader_lease_resume():
    # resume at the trim boundary while a reader holds the oldest retained
    # version: the lease keeps the params alive past the window, the
    # restarted log refuses duplicate commits, and nothing is dropped
    store = VersionedModelStore(keep_versions=3)
    store.publish(1, _params(1.0))
    lease = store.acquire(1)  # reader pins v1 before it leaves the window
    assert lease[0] == 1
    for v in range(2, 6):
        assert store.publish(v, _params(float(v))) == "candidate"
        assert store.promote(v)
    # window is {3,4,5}; v2 was freed, v1 survives only through the lease
    assert store.get(2) is None
    assert store.get(1) is not None
    np.testing.assert_array_equal(store.get(1)["w"], lease[1]["w"])
    assert store.active()[0] == 5

    # restart from the durable state (log + verdicts, no params)
    reborn = VersionedModelStore(keep_versions=3)
    reborn.import_state(store.export_state())
    # every decided version is refused on re-publish: no duplicate commit
    for v in range(1, 6):
        assert reborn.publish(v, _params(float(v))) == "duplicate"
    # the next training commit lands normally — no drop in the sequence
    assert reborn.publish(6, _params(6.0)) == "promoted"

    # releasing the lease lets the original store finally free v1
    store.release(1)
    assert store.get(1) is None
    assert store.active()[0] == 5  # the live version never trims


def test_store_rollback_pin_survives_trim_and_restart():
    store = VersionedModelStore(keep_versions=2)
    store.publish(1, _params(1.0))
    store.publish(2, _params(2.0))
    store.rollback(2, reason="canary_regression")
    before = _counters()
    # push the log far past the poisoned version's retention window
    for v in range(3, 12):
        store.publish(v, _params(float(v)))
        store.promote(v)
    assert store.get(2) is None  # params long gone
    assert store.publish(2, _params(2.0)) == "pinned"
    reborn = VersionedModelStore(keep_versions=2)
    reborn.import_state(store.export_state())
    assert reborn.publish(2, _params(2.0)) == "pinned"
    delta = (_counters().get(
        "fedml_publish_refused_total{reason=pinned}", 0.0)
        - before.get("fedml_publish_refused_total{reason=pinned}", 0.0))
    assert delta == 2


# ---------------------------------------------------------------- canary


def _linear_batches(w, n=256, batches=3, batch_size=32, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, w.shape[0])).astype(np.float32)
    y = np.argmax(x @ w, axis=1)
    cfg = CanaryConfig(batches=batches, batch_size=batch_size, seed=seed)
    return held_out_batches(x, y, cfg), cfg


def test_held_out_batches_are_seed_deterministic():
    w = _params(1.0)["w"]
    a, _ = _linear_batches(w, seed=3)
    b, _ = _linear_batches(w, seed=3)
    c, _ = _linear_batches(w, seed=4)
    for (ax, ay), (bx, by) in zip(a, b):
        np.testing.assert_array_equal(ax, bx)
        np.testing.assert_array_equal(ay, by)
    assert not np.array_equal(a[0][0], c[0][0])


def test_canary_verdict_gates():
    w = _params(1.0)["w"]
    batches, cfg = _linear_batches(w)

    def predict(params, x):
        return x @ params["w"]

    ev = CanaryEvaluator(predict, batches, cfg)
    base, finite = ev.score({"w": w})
    assert finite and base == 1.0  # labels are the model's own argmax
    # within-threshold candidate promotes; a regressed one does not
    assert ev.verdict(base, base, True)
    assert ev.verdict(base, base - cfg.regression_threshold / 2, True)
    assert not ev.verdict(base, base - 2 * cfg.regression_threshold, True)
    # non-finite is an instant fail no matter the accuracy
    assert not ev.verdict(base, 1.0, False)
    acc, finite = ev.score({"w": np.full_like(w, np.nan)})
    assert not finite


# ---------------------------------------------------------------- server


def _server(frac=0.0, batches=3, **kw):
    w = _params(1.0)["w"]
    eval_batches, _ = _linear_batches(w, batches=batches)

    def predict(params, x):
        return x @ params["w"]

    cfg = ServeConfig(enabled=True, batch_max=32,
                      canary=CanaryConfig(fraction=frac, batches=batches,
                                          batch_size=32))
    return InferenceServer(predict, cfg, eval_batches=eval_batches, **kw), w


def test_inline_canary_blocks_regression_and_nonfinite():
    server, w = _server()
    assert server.publish(1, {"w": w}) == "promoted"
    # harmless drift promotes (hot-swap)
    assert server.publish(2, {"w": w * np.float32(1.0001)}) == "promoted"
    assert server.store.active()[0] == 2
    # sign-flipped weights invert the argmax: canary regression, rollback
    assert server.publish(3, {"w": -w}) == "rolled_back"
    assert server.store.active()[0] == 2
    # NaN params never reach the request path (pre-gate, not the canary)
    assert server.publish(4, {"w": np.full_like(w, np.nan)}) == "rolled_back"
    # both poisoned versions are pinned against re-publish, even clean
    assert server.publish(3, {"w": w}) == "pinned"
    assert server.publish(4, {"w": w}) == "pinned"
    snap = _counters()
    assert snap.get("fedml_versions_promoted_total", 0) == 1  # v2 swap
    assert snap.get("fedml_rollbacks_served_total", 0) == 2
    assert snap.get("fedml_publish_refused_total{reason=pinned}", 0) == 2


def test_served_requests_ride_hot_swaps_with_zero_drops():
    results = []
    server, w = _server(
        on_result=lambda rid, ver, out: results.append((rid, ver)))
    server.publish(1, {"w": w})
    rng = np.random.default_rng(0)
    feats = rng.normal(size=(96, w.shape[0])).astype(np.float32)
    for i in range(48):
        assert server.submit(feats[i], request_id=i)
    server.pump()
    server.publish(2, {"w": w * np.float32(1.0001)})  # hot-swap mid-stream
    for i in range(48, 96):
        assert server.submit(feats[i], request_id=i)
    server.pump()
    st = server.stats()
    assert st["served"] == 96 and st["dropped"] == 0 and st["pending"] == 0
    # every request is attributed to the version that answered it, and
    # both sides of the swap actually served traffic
    assert sorted(r[0] for r in results) == list(range(96))
    by_ver = st["served_by_version"]
    assert by_ver.get(1, 0) > 0 and by_ver.get(2, 0) > 0
    assert sum(by_ver.values()) == 96
    assert _counters().get("fedml_inference_requests_total", 0) == 96


def test_worker_mode_canary_decides_candidate_asynchronously():
    server, w = _server(frac=0.5)
    server.publish(1, {"w": w})
    server.start()
    try:
        assert server.publish(2, {"w": w * np.float32(1.0001)}) == "candidate"
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            if server.store.versions().get(2) == "promoted":
                break
            time.sleep(0.005)
        assert server.store.versions().get(2) == "promoted"
        assert server.store.active()[0] == 2
        # a regressing candidate is rolled back by the same async window
        assert server.publish(3, {"w": -w}) == "candidate"
        while time.monotonic() < deadline:
            if server.store.versions().get(3) == "rolled_back":
                break
            time.sleep(0.005)
        assert server.store.versions().get(3) == "rolled_back"
        assert server.store.active()[0] == 2
    finally:
        server.stop()


def test_candidate_superseded_by_newer_publish():
    server, w = _server(frac=0.0)
    server.publish(1, {"w": w})
    server.start()
    try:
        server.publish(2, {"w": w * np.float32(1.0001)})
        # a newer commit lands before v2's canary window closes often
        # enough on a busy trainer; the loser is retired, not rolled back
        server.publish(3, {"w": w * np.float32(1.0002)})
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            if server.store.versions().get(3) in ("promoted", "rolled_back"):
                break
            time.sleep(0.005)
        assert server.store.versions().get(3) == "promoted"
    finally:
        server.stop()
    vs = server.store.versions()
    assert vs.get(2) in ("superseded", "promoted")
    assert server.store.stats()["rollbacks"] == 0


# ------------------------------------------------- simulator integration

_SIM_BASE = dict(
    dataset="mnist", model="lr", partition_method="hetero",
    partition_alpha=0.5, debug_small_data=True,
    client_num_in_total=6, client_num_per_round=4, comm_round=3,
    learning_rate=0.1, epochs=1, batch_size=8,
    frequency_of_the_test=1, random_seed=0, prefetch=False,
)

_TIMING_KEYS = {"round_time", "dispatch_time", "pack_time", "pack_wait",
                "overlap", "phases", "scan_rounds"}


def _run_sim(extra):
    from fedml_tpu.simulation import build_simulator

    args = fedml_tpu.init(config=dict(_SIM_BASE, **extra))
    sim, apply_fn = build_simulator(args)
    server = build_inference_server(args, sim, apply_fn)
    hist = sim.run(apply_fn, log_fn=None)
    return sim, server, hist


def _flat_params(sim):
    import jax

    return np.concatenate([np.asarray(l, np.float64).ravel()
                           for l in jax.tree.leaves(sim.params)])


def test_build_inference_server_disabled_returns_none():
    from fedml_tpu.simulation import build_simulator

    args = fedml_tpu.init(config=dict(_SIM_BASE))
    sim, apply_fn = build_simulator(args)
    assert build_inference_server(args, sim, apply_fn) is None
    assert sim._publisher is None


def test_training_run_publishes_and_promotes_every_round():
    sim, server, hist = _run_sim(dict(
        serve_enabled=True, canary_batches=2, canary_batch_size=32))
    rounds = _SIM_BASE["comm_round"]
    stats = server.store.stats()
    assert stats["active_version"] == rounds
    assert all(server.store.versions()[v] == "promoted"
               for v in range(1, rounds + 1))
    # the publish hand-off is attributed to its own phase; attribution is
    # by completion interval (see docs/observability.md), so round r's
    # publish lands in the record closing at round r+1's stamp — every
    # record after the first carries one
    assert all(r["phases"].get("publish", 0.0) > 0.0 for r in hist[1:])
    assert "publish" not in hist[0]["phases"]
    # and the server answers from the final model
    x = np.asarray(sim.fed.test_data_global.x[:8], np.float32)
    for i in range(8):
        assert server.submit(x[i])
    server.pump()
    assert server.stats()["served"] == 8


def test_serving_disabled_is_byte_identical():
    # serve_*/canary_* knobs present but disabled must not perturb one bit
    # of the training trajectory vs a config that never mentions serving
    sim_ref, server_ref, hist_ref = _run_sim({})
    sim_off, server_off, hist_off = _run_sim(dict(
        serve_enabled=False, canary_batches=2, canary_fraction=0.5,
        serve_batch_max=16))
    assert server_ref is None and server_off is None
    np.testing.assert_array_equal(_flat_params(sim_ref), _flat_params(sim_off))
    strip = lambda h: [{k: v for k, v in r.items() if k not in _TIMING_KEYS}
                      for r in h]
    assert strip(hist_ref) == strip(hist_off)
    assert "publish" not in {k for r in hist_ref for k in r["phases"]}


# ----------------------------------------------------- mixed-traffic load


@pytest.mark.loadgen
def test_mixed_loadgen_zero_drops_across_five_hot_swaps():
    from fedml_tpu.cross_silo.loadgen import run_mixed_loadgen

    report = run_mixed_loadgen(duration_s=1.0, infer_producers=2,
                               checkin_producers=1, commit_interval_s=0.05,
                               min_swaps=5, seed=0)
    assert report.ok, report.summary()
    # the acceptance floor: >=10k req/s served while training commits
    # versions underneath, zero dropped requests across >=5 hot-swaps
    assert report.served_rate >= 10_000.0, report.summary()
    assert report.dropped == 0
    assert report.swaps >= 5
    assert report.train_processed > 0       # check-ins share the queue
    assert report.canary_served > 0         # candidates saw live traffic
    assert len(report.served_by_version) >= 5
    rec = report.json_record()
    assert rec["ok"] and rec["queue_depth_bounded"]


@pytest.mark.loadgen
def test_mixed_loadgen_from_args_maps_knobs():
    from fedml_tpu.cross_silo.loadgen import run_mixed_loadgen_from_args

    args = fedml_tpu.init(config=dict(
        mixed_duration_s=0.2, mixed_infer_producers=1,
        mixed_checkin_producers=1, mixed_min_swaps=1,
        mixed_queue_maxsize=1024, mixed_seed=3))
    report = run_mixed_loadgen_from_args(args)
    assert report.queue_maxsize == 1024
    assert report.min_swaps == 1
    assert report.dropped == 0


# -------------------------------------------------- poisoned-rollout drill


@pytest.mark.chaos
def test_rollout_drill_blocks_poison_and_serves_within_gate():
    from fedml_tpu.cross_silo.chaos import run_rollout_drill

    result = run_rollout_drill()
    assert result.ok, result.summary()
    assert result.poison_status == "rolled_back"
    assert result.repub_status == "pinned"          # never re-promoted
    assert result.rollbacks_counter >= 1            # counter moved too
    assert result.served_acc_gap <= result.max_acc_delta
    by_v = {t["version"]: t for t in result.trajectory}
    assert by_v[result.poison_version]["status"] == "rolled_back"
    # serving kept answering from last-good while the poison was refused
    assert by_v[result.poison_version]["served_acc"] is not None
    rec = result.json_record()
    assert rec["ok"] and rec["poison_kind"] == "sign_flip"


@pytest.mark.chaos
def test_rollout_drill_nonfinite_kind():
    from fedml_tpu.cross_silo.chaos import run_rollout_drill

    result = run_rollout_drill(rollout_poison_kind="nan")
    assert result.ok, result.summary()
    assert result.poison_status == "rolled_back"
