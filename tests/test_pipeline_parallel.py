"""GPipe-style pipeline parallelism: schedule correctness vs an unpipelined
stack, and learning through the pipelined backward (scan + ppermute VJP)."""

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.parallel.pipeline import (
    PipelineConfig,
    PipelinedLMTrainer,
    make_pipe_mesh,
)


def _data(B=8, T=16, vocab=64, seed=0):
    rng = np.random.default_rng(seed)
    toks = rng.integers(0, vocab, size=(B, T)).astype(np.int32)
    return toks, np.roll(toks, -1, axis=1)


def test_pipeline_matches_sequential_forward():
    """pp=4 pipelined forward == the same stages applied sequentially."""
    cfg = PipelineConfig(pp=4, dp=1, microbatches=4)
    mesh = make_pipe_mesh(cfg, devices=jax.devices()[:4])
    tr = PipelinedLMTrainer(cfg, vocab_size=64, dim=32, num_heads=4,
                            num_layers=4, max_len=16, mesh=mesh)
    toks, _ = _data()

    h = tr.embed.apply(tr.params["embed"], jnp.asarray(toks))
    h = h + tr.params["pos"][None, : toks.shape[1]]

    # reference: apply stage s params in order, no pipeline
    ref = h
    for s in range(cfg.pp):
        stage_s = jax.tree.map(lambda a, s=s: a[s], tr.params["stages"])
        ref = tr.stage.apply(stage_s, ref)

    # pipelined: run the jitted loss path up to the pipeline output by
    # reusing the internal schedule
    from fedml_tpu.parallel.pipeline import _pipeline_apply
    from jax.sharding import PartitionSpec as P
    from fedml_tpu.parallel.mesh import AXIS_DATA, AXIS_PIPE

    M = cfg.microbatches
    mb = h.shape[0] // M
    h_mb = h.reshape(M, mb, h.shape[1], h.shape[2])

    def inner(stage_slice, x_mb):
        local = jax.tree.map(lambda a: a[0], stage_slice)
        return _pipeline_apply(
            lambda p, x: tr.stage.apply(p, x), local, x_mb,
            pp=cfg.pp, axis=AXIS_PIPE,
        )

    out = jax.shard_map(
        inner, mesh=mesh,
        in_specs=(jax.tree.map(lambda _: P(AXIS_PIPE), tr.params["stages"]),
                  P(None, AXIS_DATA)),
        out_specs=P(None, AXIS_DATA),
        check_vma=False,
    )(tr.params["stages"], h_mb)
    out = out.reshape(ref.shape)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_pipeline_trainer_learns():
    """dp2 x pp4 end-to-end: loss decreases through the pipelined backward."""
    cfg = PipelineConfig(pp=4, dp=2, microbatches=4, lr=3e-3)
    mesh = make_pipe_mesh(cfg, devices=jax.devices()[:8])
    tr = PipelinedLMTrainer(cfg, vocab_size=64, dim=32, num_heads=4,
                            num_layers=8, max_len=16, mesh=mesh)
    toks, tgt = _data(B=8)
    losses = [tr.step(toks, tgt) for _ in range(12)]
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] - 0.5, losses
