"""Tier-1 lint: no bare print() in fedml_tpu/ library code (scripts/check_no_print.py)."""

import os
import subprocess
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_no_bare_print_in_library():
    r = subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "scripts", "check_no_print.py")],
        capture_output=True, text=True,
    )
    assert r.returncode == 0, r.stderr


def test_lint_catches_a_planted_print(tmp_path):
    """The checker must actually flag a bare call — but not a bare
    reference (``log_fn=print`` stays legal)."""
    sys.path.insert(0, os.path.join(REPO_ROOT, "scripts"))
    try:
        from check_no_print import find_print_calls
    finally:
        sys.path.pop(0)
    p = tmp_path / "mod.py"
    p.write_text("def f(log_fn=print):\n    print('hot path')\n")
    hits = find_print_calls(str(p))
    assert [ln for ln, _ in hits] == [2]
