"""Test config: force an 8-device virtual CPU mesh before any JAX backend init.

In this image, sitecustomize imports jax and registers the TPU plugin at
interpreter start, so jax is already in sys.modules here — but no backend has
been *initialized* yet. Overriding jax_platforms + XLA_FLAGS before the first
device lookup keeps tests entirely on virtual CPU devices (the real TPU chip
is reserved for bench runs; a killed test run would otherwise wedge the
device-tunnel session claim).
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
assert len(jax.devices()) == 8, "expected 8 virtual CPU devices for sharding tests"
