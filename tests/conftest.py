"""Test config: force an 8-device virtual CPU mesh before any JAX backend init.

In this image, sitecustomize imports jax and registers the TPU plugin at
interpreter start, so jax is already in sys.modules here — but no backend has
been *initialized* yet. Overriding jax_platforms + XLA_FLAGS before the first
device lookup keeps tests entirely on virtual CPU devices (the real TPU chip
is reserved for bench runs; a killed test run would otherwise wedge the
device-tunnel session claim).
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
assert len(jax.devices()) == 8, "expected 8 virtual CPU devices for sharding tests"

import pytest  # noqa: E402

# Measured-duration tiering (VERDICT r2 weak #5): tests whose call time
# exceeded ~5s in the full-suite timing run are auto-marked `slow` so
# `pytest -m "not slow"` is a quick CI tier. Matching is by test-function
# name substring; explicit @pytest.mark.slow decorations still apply.
SLOW_TEST_NAMES = (
    "test_batchnorm_fedopt_splits_server_update",
    "test_batchnorm_resnet_trains_and_averages_stats",
    "test_federated_detection_learns_localization",
    "test_fednas_darts_search_runs",
    "test_fedgkt_learns",
    "test_fedseg_unet_learns",
    "test_fedgan_round_runs",
    "test_bucketed_beats_even_on_skewed_cohort",
    "test_bucketed_matches_even_numerics",
    "test_fednlp_seq2seq_learns",
    "test_fednlp_span_extraction_learns",
    "test_fednlp_seq_tagging_learns",
    "test_fedgraphnn_link_prediction_learns",
    "test_distributed_lm_ulysses_matches_ring_forward",
    "test_distributed_lm_trains",
    "test_ulysses_attention_matches_dense",
    "test_param_specs_megatron_layout",
    "test_pipeline_matches_sequential_forward",
    "test_pipeline_trainer_learns",
    "test_engine_matches_reference_torch_loop",
    "test_fednlp_text_classification_learns",
    "test_example_config_loads_and_resolves",
    "test_hierarchical_fl_learns",
    "test_moe_block_top2_learns_routing",
    "test_moe_learns_routing",
    "test_moe_block_runs_and_shards",
    "test_dp_training_still_learns",
    "test_dp_noise_engages_and_is_seeded",
    "test_packed_checkpoint_resume_matches_uninterrupted",
    "test_packed_with_momentum_and_prox",
    "test_packed_on_mesh_matches_sp",
    "test_packed_matches_even_sp",
    "test_packed_matches_even_multiepoch",
    "test_packed_client_dropout_matches_even",
    "test_fediot_autoencoder_detects_anomalies",
    "test_mesh_matches_sp",
    "test_mesh_params_replicated_and_finite",
    "test_flash_gradients_match_dense",
    "test_flash_gradients_long_context_T1024",
    "test_agent_daemon_end_to_end",
    "test_mobile_artifact_roundtrip",
    "test_checkpoint_resume_matches_uninterrupted",
    "test_grpc_mtls_roundtrip_and_plaintext_refused",
    "test_bilevel_search_moves_alphas_and_learns",
    "test_search_then_retrain_beats_random_genotype",
    "test_hf_bert_checkpoint_logit_equality",
    "test_federated_finetune_from_imported_weights",
    "test_decentralized_dsgd_consensus_and_learning",
    "test_import_shape_check_fails_loudly",
    "test_batchnorm_rejected_for_stats_corrupting_optimizers",
    "test_mobile_lenet_learns",
    "test_fedgraphnn_gcn_learns",
    "test_digits_real_dataset_learns",
    "test_fedopt_adaptive_server_optimizers_learn",
    "test_sync_batchnorm_matches_full_batch_stats",
    "test_efficientnet_family_scales",
)


def pytest_collection_modifyitems(config, items):
    for item in items:
        if any(name in item.name for name in SLOW_TEST_NAMES):
            item.add_marker(pytest.mark.slow)
