"""Hierarchical cross-silo: silo-internal data-parallel mesh replaces DDP."""

import threading

import numpy as np

import fedml_tpu
from fedml_tpu.comm import LoopbackHub
from fedml_tpu.cross_silo import FedML_Horizontal
from fedml_tpu.parallel import AXIS_DATA, MeshConfig, create_mesh


def test_hierarchical_silo_mesh_run():
    import jax

    args = fedml_tpu.init(config=dict(
        dataset="mnist", model="lr", debug_small_data=True,
        client_num_in_total=2, client_num_per_round=2, comm_round=2,
        learning_rate=0.1, batch_size=16, frequency_of_the_test=1,
        random_seed=0,
    ))
    hub = LoopbackHub()
    # silo-internal 4-way data-parallel mesh (the reference runs DDP across
    # silo GPUs here, trainer_dist_adapter.py:66-68)
    silo_mesh = create_mesh(
        MeshConfig(axes=((AXIS_DATA, 4),)), devices=jax.devices()[:4]
    )
    server = FedML_Horizontal(args, 0, 2, backend="LOOPBACK", hub=hub)
    clients = [
        FedML_Horizontal(args, rank, 2, backend="LOOPBACK", hub=hub, mesh=silo_mesh)
        for rank in (1, 2)
    ]
    threads = [threading.Thread(target=c.run, daemon=True) for c in clients]
    for t in threads:
        t.start()
    server.start()
    server.run()
    for t in threads:
        t.join(timeout=60)
    assert len(server.history) == 2
    assert np.isfinite(server.history[-1]["test_acc"])
