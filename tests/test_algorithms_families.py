"""VFL, SplitNN, TurboAggregate, FedGKT, FedGAN, FedNAS, FedSeg."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import fedml_tpu
from fedml_tpu.simulation import build_simulator
from fedml_tpu.simulation.fed_sim import SimConfig


def test_vertical_fl_learns():
    from fedml_tpu.algorithms.vertical_fl import VFLSimulator

    rng = np.random.default_rng(0)
    n, d = 600, 10
    w_true = rng.normal(size=(d, 3))
    x = rng.normal(size=(n + 200, d)).astype(np.float32)
    y = np.argmax(x @ w_true + 0.1 * rng.normal(size=(n + 200, 3)), axis=1)
    sim = VFLSimulator(x[:n], y[:n], x[n:], y[n:], n_parties=3, n_classes=3,
                       lr=0.5, batch_size=64)
    hist = sim.run(epochs=8)
    assert hist[-1]["test_acc"] > 0.8, hist[-1]


def test_split_nn_learns():
    from fedml_tpu.algorithms.split_nn import SplitNNSimulator
    from fedml_tpu import data as data_mod

    args = fedml_tpu.init(config=dict(
        dataset="mnist", debug_small_data=True, client_num_in_total=4,
        partition_method="homo", random_seed=0))
    fed, _ = data_mod.load(args)
    import flax.linen as nn

    class Body(nn.Module):
        @nn.compact
        def __call__(self, x):
            x = x.reshape((x.shape[0], -1))
            return nn.relu(nn.Dense(64)(x))

    class Head(nn.Module):
        @nn.compact
        def __call__(self, h):
            return nn.Dense(10)(h)

    body, head = Body(), Head()
    x0 = jnp.zeros((1, 28, 28, 1))
    cp = body.init(jax.random.PRNGKey(0), x0)
    sp = head.init(jax.random.PRNGKey(1), body.apply(cp, x0))
    sim = SplitNNSimulator(body.apply, head.apply, cp, sp, lr=0.2)
    pk = fed.pack_clients([0, 1, 2, 3], batch_size=16, num_batches=4)
    first = sim.run_epoch(pk.x, pk.y, pk.mask)
    for _ in range(3):
        last = sim.run_epoch(pk.x, pk.y, pk.mask)
    assert last["train_loss"] < first["train_loss"]
    test = fed.test_data_global
    preds = jnp.argmax(sim.predict(test.x[:200]), -1)
    assert float((preds == jnp.asarray(test.y[:200])).mean()) > 0.5


def test_turbo_aggregate_matches_fedavg_closely():
    from fedml_tpu.algorithms import LocalTrainConfig, make_local_update
    from fedml_tpu.algorithms.turbo_aggregate import TurboAggregateSimulator
    from fedml_tpu import data as data_mod, models as models_mod

    args = fedml_tpu.init(config=dict(
        dataset="mnist", model="lr", debug_small_data=True,
        client_num_in_total=4, client_num_per_round=4, comm_round=3,
        learning_rate=0.1, batch_size=8, frequency_of_the_test=1, random_seed=0))
    fed, output_dim = data_mod.load(args)
    model = models_mod.create(args, output_dim)
    variables = models_mod.init_params(
        model, jax.random.PRNGKey(0), models_mod.sample_input_for(args, fed))

    def apply_fn(v, x, train=False, rngs=None):
        return model.apply(v, x, train=train)

    lu = make_local_update(apply_fn, LocalTrainConfig(lr=0.1, epochs=1))
    sim = TurboAggregateSimulator(
        fed, lu, variables,
        SimConfig(comm_round=3, client_num_in_total=4, client_num_per_round=4,
                  batch_size=8, frequency_of_the_test=1),
        privacy_guarantee=1, q_bits=14)
    hist = sim.run(apply_fn, log_fn=None)
    assert hist[0]["train_loss"] > hist[-1]["train_loss"]
    assert hist[-1]["test_acc"] > 0.5


def test_fedgkt_learns():
    from fedml_tpu.algorithms.fedgkt import FedGKTSimulator
    from fedml_tpu.models import GKTClientNet, GKTServerNet
    from fedml_tpu import data as data_mod

    args = fedml_tpu.init(config=dict(
        dataset="cifar10", debug_small_data=True, client_num_in_total=3,
        partition_method="homo", random_seed=0))
    fed, _ = data_mod.load(args)
    cnet = GKTClientNet(num_classes=10)
    snet = GKTServerNet(num_classes=10)
    x0 = jnp.zeros((1, 32, 32, 3))
    cp = cnet.init(jax.random.PRNGKey(0), x0)
    h0, _ = cnet.apply(cp, x0)
    sp = snet.init(jax.random.PRNGKey(1), h0)
    sim = FedGKTSimulator(
        fed, cnet.apply, snet.apply, cp, sp,
        SimConfig(comm_round=3, client_num_in_total=3, client_num_per_round=3,
                  batch_size=16), lr=0.05)
    hist = sim.run(log_fn=None)
    assert hist[0]["client_loss"] > hist[-1]["client_loss"]
    acc = sim.evaluate(cnet.apply, snet.apply)
    assert np.isfinite(acc)


def test_fedgan_round_runs():
    from fedml_tpu.algorithms.fedgan import get_fedgan_algorithm
    from fedml_tpu.models import Discriminator, Generator
    from fedml_tpu.simulation.fed_sim import FedSimulator
    from fedml_tpu import data as data_mod

    args = fedml_tpu.init(config=dict(
        dataset="mnist", debug_small_data=True, client_num_in_total=3,
        partition_method="homo", random_seed=0))
    fed, _ = data_mod.load(args)
    gen, disc = Generator(latent_dim=16), Discriminator()
    gp = gen.init(jax.random.PRNGKey(0), jnp.zeros((1, 16)))
    dp = disc.init(jax.random.PRNGKey(1), jnp.zeros((1, 28, 28, 1)))
    alg = get_fedgan_algorithm(gen.apply, disc.apply, latent_dim=16, lr=1e-3)
    sim = FedSimulator(
        fed, alg, {"gen": gp, "disc": dp},
        SimConfig(comm_round=2, client_num_in_total=3, client_num_per_round=3,
                  batch_size=8, num_local_batches=2))
    hist = sim.run(apply_fn=None, log_fn=None)
    assert len(hist) == 2
    assert np.isfinite(hist[-1]["train_loss"])


def test_fednas_darts_search_runs():
    from fedml_tpu.models import derive_genotype

    args = fedml_tpu.init(config=dict(
        dataset="cifar10", model="darts", debug_small_data=True,
        client_num_in_total=3, client_num_per_round=3, comm_round=2,
        learning_rate=0.05, batch_size=8, frequency_of_the_test=2,
        random_seed=0))
    sim, apply_fn = build_simulator(args)
    hist = sim.run(apply_fn, log_fn=None)
    assert hist[0]["train_loss"] >= hist[-1]["train_loss"] or hist[-1]["train_loss"] < 3.0
    genotype = derive_genotype(sim.params)
    assert len(genotype) == 4  # 2 cells x 2 mixed ops
    assert all(g["op"] in ("conv3", "conv5", "avgpool", "identity") for g in genotype)


@pytest.mark.slow
def test_fedseg_transunet_learns():
    """TransUNet (reference app/fedcv/image_segmentation/model/transunet):
    CNN encoder + ViT bottleneck must train federated and segment."""
    args = fedml_tpu.init(config=dict(
        dataset="seg_synthetic", model="transunet", debug_small_data=True,
        client_num_in_total=2, client_num_per_round=2, comm_round=3,
        partition_method="homo", learning_rate=0.05, batch_size=8,
        frequency_of_the_test=3, random_seed=0))
    sim, apply_fn = build_simulator(args)
    hist = sim.run(apply_fn, log_fn=None)
    assert hist[0]["train_loss"] > hist[-1]["train_loss"]
    assert hist[-1]["test_acc"] > 0.9, hist[-1]


@pytest.mark.slow
def test_fedseg_deeplab_learns_and_beats_unet_control():
    """DeepLabV3+ (reference app/fedcv/image_segmentation/model/
    deeplabV3_plus.py) trains federated, learns, and — VERDICT r3 #4 —
    earns its ASPP/decoder depth: same federated budget on the 4-class
    medical segmentation task, at least UNetLite's per-pixel accuracy.
    (slow: ~20 distinct conv shapes to compile on one CPU core; one
    combined test so the DeepLab compile is paid once)"""
    def run(model):
        args = fedml_tpu.init(config=dict(
            dataset="fets2021", model=model, debug_small_data=True,
            client_num_in_total=2, client_num_per_round=2, comm_round=4,
            partition_method="homo", learning_rate=0.05, batch_size=8,
            frequency_of_the_test=4, random_seed=0))
        sim, apply_fn = build_simulator(args)
        return sim.run(apply_fn, log_fn=None)

    h_unet = run("unet")
    h_dl = run("deeplabv3_plus")
    assert h_dl[0]["train_loss"] > h_dl[-1]["train_loss"]
    assert h_dl[-1]["test_acc"] >= h_unet[-1]["test_acc"] - 0.02, (
        h_dl[-1], h_unet[-1])
    assert h_dl[-1]["test_acc"] > 0.85, h_dl[-1]


def test_fedseg_unet_learns():
    args = fedml_tpu.init(config=dict(
        dataset="seg_synthetic", model="unet", debug_small_data=True,
        client_num_in_total=3, client_num_per_round=3, comm_round=3,
        partition_method="homo", learning_rate=0.1, batch_size=8,
        frequency_of_the_test=2, random_seed=0))
    sim, apply_fn = build_simulator(args)
    hist = sim.run(apply_fn, log_fn=None)
    assert hist[-1]["train_loss"] < hist[0]["train_loss"]
    # per-pixel accuracy should beat majority-class-ish quickly
    assert hist[-1]["test_acc"] > 0.9
