"""LM training ops: chunked cross-entropy and block rematerialization.

These are the memory levers of the MFU flagship (scripts/bench_lm_mfu.py):
both must be pure memory/time tradeoffs — numerics identical to the naive
formulations.
"""

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.ops.losses import chunked_lm_cross_entropy


def _plain_ce(h, w, t):
    logz = jax.nn.log_softmax((h @ w).astype(jnp.float32))
    return -jnp.mean(jnp.take_along_axis(logz, t[..., None], -1))


def test_chunked_ce_matches_plain():
    rng = np.random.RandomState(0)
    B, T, D, V = 2, 12, 16, 50
    h = jnp.asarray(rng.randn(B, T, D), jnp.float32)
    w = jnp.asarray(rng.randn(D, V), jnp.float32) * 0.1
    t = jnp.asarray(rng.randint(0, V, (B, T)))
    np.testing.assert_allclose(_plain_ce(h, w, t),
                               chunked_lm_cross_entropy(h, w, t, chunk=4),
                               rtol=1e-6)
    g1 = jax.grad(chunked_lm_cross_entropy, (0, 1))(h, w, t, chunk=4)
    g2 = jax.grad(_plain_ce, (0, 1))(h, w, t)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-7)


def test_chunked_ce_rejects_indivisible_t():
    h = jnp.zeros((1, 10, 4))
    w = jnp.zeros((4, 7))
    t = jnp.zeros((1, 10), jnp.int32)
    try:
        chunked_lm_cross_entropy(h, w, t, chunk=4)
        raise AssertionError("expected ValueError")
    except ValueError:
        pass


def test_transformer_lm_remat_identical():
    """remat=True must change memory behavior only: outputs and grads are
    bit-compatible with the non-remat model on the same params."""
    from fedml_tpu.models.transformer import TransformerLM

    kw = dict(vocab_size=64, dim=32, num_heads=4, num_layers=2, max_len=16)
    m0 = TransformerLM(**kw)
    m1 = TransformerLM(**kw, remat=True)
    toks = jnp.asarray(np.random.RandomState(0).randint(0, 64, (2, 16)))
    p = m0.init(jax.random.PRNGKey(0), toks)
    np.testing.assert_allclose(m0.apply(p, toks), m1.apply(p, toks),
                               rtol=1e-6)

    def loss(m):
        return lambda p: (m.apply(p, toks).astype(jnp.float32) ** 2).mean()

    g0 = jax.grad(loss(m0))(p)
    g1 = jax.grad(loss(m1))(p)
    for a, b in zip(jax.tree_util.tree_leaves(g0),
                    jax.tree_util.tree_leaves(g1)):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


def test_return_hidden_head_equivalence():
    """apply(return_hidden) @ head == apply() — the chunked-CE contract."""
    from fedml_tpu.models.transformer import TransformerLM

    m = TransformerLM(vocab_size=64, dim=32, num_heads=4, num_layers=2,
                      max_len=16)
    toks = jnp.asarray(np.random.RandomState(1).randint(0, 64, (2, 16)))
    p = m.init(jax.random.PRNGKey(0), toks)
    full = m.apply(p, toks)
    hid = m.apply(p, toks, return_hidden=True)
    np.testing.assert_allclose(full, hid @ p["params"]["head"]["kernel"],
                               rtol=1e-5, atol=1e-5)
