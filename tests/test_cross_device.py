"""Cross-device (Beehive): blob codec, server round with device blobs, LSA."""

import threading

import numpy as np

import fedml_tpu
from fedml_tpu.comm import LoopbackHub, Message
from fedml_tpu.cross_device import (
    LSAAggregator,
    ServerMNN,
    decode_model_blob,
    encode_model_blob,
)
from fedml_tpu.core.secure_agg import LightSecAggClient, LightSecAggConfig, LightSecAggServer
from fedml_tpu.cross_silo import MyMessage


def test_model_blob_roundtrip():
    params = {"layer": {"kernel": np.random.randn(4, 3).astype(np.float32),
                        "bias": np.zeros(3, np.float32)}}
    blob = encode_model_blob(params)
    assert isinstance(blob, bytes)
    out = decode_model_blob(blob, params)
    np.testing.assert_array_equal(out["layer"]["kernel"], params["layer"]["kernel"])


def test_server_mnn_round_with_device_blobs(tmp_path):
    """Simulated phones: reply to INIT/SYNC with a serialized delta blob."""
    args = fedml_tpu.init(config=dict(
        dataset="mnist", model="lr", debug_small_data=True,
        client_num_in_total=2, client_num_per_round=2, comm_round=2,
        learning_rate=0.1, batch_size=8, frequency_of_the_test=1,
        random_seed=0, global_model_file_path=str(tmp_path / "global.blob"),
    ))
    from fedml_tpu import data as data_mod, models as models_mod
    import jax

    fed_data, output_dim = data_mod.load(args)
    model = models_mod.create(args, output_dim)
    sample = models_mod.sample_input_for(args, fed_data)
    variables = models_mod.init_params(model, jax.random.PRNGKey(0), sample)

    def apply_fn(v, x, train=False, rngs=None):
        return model.apply(v, x, train=train)

    hub = LoopbackHub()
    server = ServerMNN(args, fed_data, variables, apply_fn=apply_fn,
                       backend="LOOPBACK", hub=hub)

    template = variables

    class Phone:
        """Stand-in for the Android client: zero-delta blob uploads."""

        def __init__(self, rank):
            self.rank = rank
            self.comm = __import__("fedml_tpu.comm.loopback", fromlist=["LoopbackCommManager"]) \
                .LoopbackCommManager(rank=rank, size=3, hub=hub)
            self.comm.add_observer(self)

        def receive_message(self, t, msg):
            if t == MyMessage.MSG_TYPE_S2C_CHECK_CLIENT_STATUS:
                r = Message(MyMessage.MSG_TYPE_C2S_CLIENT_STATUS, self.rank, 0)
                r.add_params(MyMessage.MSG_ARG_KEY_CLIENT_STATUS,
                             MyMessage.MSG_CLIENT_STATUS_IDLE)
                self.comm.send_message(r)
            elif t in (MyMessage.MSG_TYPE_S2C_INIT_CONFIG,
                       MyMessage.MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT):
                delta = jax.tree.map(lambda p: np.zeros_like(np.asarray(p)), template)
                r = Message(MyMessage.MSG_TYPE_C2S_SEND_MODEL_TO_SERVER, self.rank, 0)
                r.add_params(MyMessage.MSG_ARG_KEY_MODEL_PARAMS, encode_model_blob(delta))
                r.add_params(MyMessage.MSG_ARG_KEY_NUM_SAMPLES, 10)
                self.comm.send_message(r)
            elif t == MyMessage.MSG_TYPE_S2C_FINISH:
                self.comm.stop_receive_message()

        def run(self):
            self.comm.handle_receive_message()

    phones = [Phone(1), Phone(2)]
    threads = [threading.Thread(target=p.run, daemon=True) for p in phones]
    for t in threads:
        t.start()
    hist = server.run()
    for t in threads:
        t.join(timeout=30)
    assert len(hist) == 2
    assert (tmp_path / "global.blob").exists()


def test_lsa_aggregator_protocol():
    """Full LightSecAgg message-level flow against LSAAggregator."""
    n, u, t = 5, 3, 1
    updates = [{"w": np.full(4, 0.1 * (i + 1), np.float32)} for i in range(n)]
    cfg = LightSecAggConfig(num_clients=n, target_active=u, privacy_guarantee=t,
                            model_dimension=4, q_bits=12)
    clients = [LightSecAggClient(cfg, i, seed=7) for i in range(n)]
    encoded = {i: clients[i].encode_mask_shares() for i in range(n)}
    agg = LSAAggregator(cfg, updates[0])  # template params double as model
    agg.model_params = {"w": np.zeros(4, np.float32)}
    active = [0, 1, 3]
    for cid in active:
        agg.add_masked_update(cid, clients[cid].mask_update(updates[cid]))
    assert agg.check_all_updates_received(len(active))
    # surviving clients send their aggregate-mask shares
    for j in active[:u]:
        share = LightSecAggServer.aggregate_encoded_masks(
            {i: encoded[i][j] for i in range(n)}, active, cfg.prime
        )
        agg.add_local_aggregate_encoded_mask(j, share)
    assert agg.check_whether_all_aggregate_encoded_mask_receive()
    out = agg.aggregate()
    expected = sum(updates[i]["w"] for i in active) / len(active)
    np.testing.assert_allclose(out["w"], expected, atol=1e-2)
