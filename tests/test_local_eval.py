"""Per-client local-test evaluation (reference ``_local_test_on_all_clients``,
``/root/reference/python/fedml/simulation/sp/fedavg/fedavg_api.py:188-246``)
and the ``test_on_the_server`` hook (``FedAVGAggregator.py:130``)."""

import numpy as np
import pytest

import fedml_tpu
from fedml_tpu.simulation import build_simulator


def _args(**over):
    base = dict(
        dataset="mnist", model="lr", debug_small_data=True,
        client_num_in_total=8, client_num_per_round=4, comm_round=3,
        learning_rate=0.1, epochs=1, batch_size=10, backend="sp",
        frequency_of_the_test=2, random_seed=0,
    )
    base.update(over)
    return fedml_tpu.init(config=base)


def test_local_test_on_all_clients_matches_per_client_loop():
    """The one-program segmented eval must agree with an explicit
    client-by-client evaluation of the same params (the reference's loop
    semantics), per client and in the weighted aggregate."""
    args = _args(local_test_on_all_clients=True)
    sim, apply_fn = build_simulator(args)
    res = sim.local_test_on_all_clients(apply_fn)
    pc = res["per_client"]

    import jax.numpy as jnp

    keys = sorted(sim.fed.train_data_local_dict.keys())
    for split, d in (("train", sim.fed.train_data_local_dict),
                     ("test", sim.fed.test_data_local_dict)):
        for i, k in enumerate(keys):
            pair = d.get(k)
            if pair is None or len(pair) == 0:
                continue
            logits = apply_fn(sim.params, jnp.asarray(pair.x), train=False)
            logz = np.asarray(
                jnp.take_along_axis(
                    jnp.log(jnp.clip(jnp.asarray(
                        np.exp(np.asarray(logits, np.float64))
                        / np.exp(np.asarray(logits, np.float64)).sum(
                            -1, keepdims=True)), 1e-30)),
                    jnp.asarray(pair.y)[..., None], axis=-1)[..., 0])
            loss = -float(logz.sum()) / len(pair)
            acc = float(
                (np.asarray(np.argmax(logits, -1)) == pair.y).mean())
            assert pc[f"{split}_loss"][i] == pytest.approx(loss, rel=2e-3), (
                split, k)
            assert pc[f"{split}_acc"][i] == pytest.approx(acc, abs=1e-6), (
                split, k)
            assert pc[f"{split}_samples"][i] == len(pair)

    # weighted aggregates = sum over included clients / total samples
    n = np.asarray(pc["test_samples"])
    inc = n > 0
    agg_acc = (np.asarray(pc["test_acc"]) * n)[inc].sum() / n[inc].sum()
    assert res["local_test_acc"] == pytest.approx(float(agg_acc), abs=1e-6)


def test_history_carries_local_metrics_at_eval_rounds():
    args = _args(local_test_on_all_clients=True)
    history = fedml_tpu.run_simulation(args=args)
    eval_recs = [h for h in history if "test_acc" in h]
    assert eval_recs, "no eval rounds recorded"
    for rec in eval_recs:
        for key in ("local_train_acc", "local_train_loss",
                    "local_test_acc", "local_test_loss"):
            assert key in rec, key
        pc = rec["per_client"]
        assert len(pc["train_acc"]) == 8
        assert len(pc["test_acc"]) == 8
    # training on MNIST LR: local-train accuracy should beat random fast
    assert eval_recs[-1]["local_train_acc"] > 0.5
    # non-eval rounds must not pay the cost
    non_eval = [h for h in history if "test_acc" not in h]
    assert all("local_train_acc" not in h for h in non_eval)


def test_shared_test_pair_deduplicated():
    """Default loaders hand every client the SAME global-test ArrayPair —
    the segmented eval must evaluate it once, not materialize C copies."""
    args = _args(local_test_on_all_clients=True)
    sim, apply_fn = build_simulator(args)
    tdict = sim.fed.test_data_local_dict
    keys = sorted(tdict.keys())
    if len({id(tdict[k]) for k in keys}) != 1:
        pytest.skip("loader no longer shares one test pair")
    kind, batched, rep = sim._local_eval_batches("test")
    assert kind == "direct"
    n_one = len(tdict[keys[0]])
    total_rows = batched[0].shape[0] * batched[0].shape[1]
    assert total_rows < 2 * n_one, "shared pair was duplicated per client"
    assert (rep == rep[0]).all() and rep[0] == 0
    res = sim.local_test_on_all_clients(apply_fn)
    pc = res["per_client"]
    # every client reports the same (shared-set) stats, and the weighted
    # aggregate equals the single-set value
    assert len(set(pc["test_acc"])) == 1
    assert res["local_test_acc"] == pytest.approx(pc["test_acc"][0])
    g = sim.evaluate(apply_fn)
    assert res["local_test_acc"] == pytest.approx(g["test_acc"], abs=1e-6)


def test_server_tester_hook_replaces_default_eval():
    """Reference FedAVGAggregator.py:130: a truthy test_on_the_server
    return skips the default evaluation entirely."""
    calls = []

    class Tester:
        def test_on_the_server(self, train_dict, test_dict, device, args):
            calls.append((len(train_dict), len(test_dict), device, args))
            return {"custom_metric": 0.75}

    args = _args()
    args.server_tester = Tester()
    history = fedml_tpu.run_simulation(args=args)
    assert calls and calls[0][:2] == (8, 8)
    # reference signature: real device + the original args, not None
    assert calls[0][2] is not None and calls[0][3] is args
    eval_recs = [h for h in history if "custom_metric" in h]
    assert eval_recs, "hook result missing from history"
    assert all("test_acc" not in h for h in history), (
        "default eval must be skipped when the hook handles testing")


def test_server_tester_falsy_falls_through():
    class Tester:
        def test_on_the_server(self, train_dict, test_dict, device, args):
            return None

    args = _args()
    args.server_tester = Tester()
    history = fedml_tpu.run_simulation(args=args)
    assert any("test_acc" in h for h in history)
