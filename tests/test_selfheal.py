"""Self-healing rounds: seeded byzantine drills end to end.

Acceptance drills for PR 4: a cohort with 30% NaN or 10×-scaled uploads must
converge within 2% of the clean run's eval under multi-Krum + quarantine,
while the undefended run visibly diverges; the divergence watchdog must
detect a poisoned round, roll the global state back, and re-run without the
implicated clients — in both the simulation engine and the cross-silo
deployment (where the corruption enters through the comm-plane fault
injector, not the aggregation path).
"""

import numpy as np
import pytest

import fedml_tpu
from fedml_tpu.comm.resilience import FaultPlan, corrupt_update_tree
from fedml_tpu.core import telemetry
from fedml_tpu.simulation import build_simulator


def _run(**kw):
    cfg = dict(
        dataset="digits", model="lr", partition_method="homo",
        client_num_in_total=10, client_num_per_round=10, comm_round=12,
        learning_rate=0.3, epochs=1, batch_size=32,
        frequency_of_the_test=11, random_seed=0,
    )
    cfg.update(kw)
    args = fedml_tpu.init(config=cfg)
    sim, apply_fn = build_simulator(args)
    return sim.run(apply_fn, log_fn=None)


# --- simulator drills --------------------------------------------------------


def test_nan_drill_defended_matches_clean_undefended_diverges():
    """30% all-NaN uploads: multi-Krum + sanitizer stays within 2% of the
    clean run and quarantines every attacker; undefended FedAvg goes
    non-finite and collapses to chance accuracy."""
    clean = _run()
    defended = _run(
        attack_type="nan", attacker_ratio=0.3,
        federated_optimizer="FedAvg_robust", defense_type="multi_krum",
        sanitize_updates=True)
    undefended = _run(attack_type="nan", attacker_ratio=0.3)

    assert defended[-1]["test_acc"] >= clean[-1]["test_acc"] - 0.02, (
        clean[-1]["test_acc"], defended[-1]["test_acc"])
    # the 3 seeded attackers are caught every round (same seed -> same mask)
    assert all(len(h["quarantined"]) == 3 for h in defended)
    assert np.isfinite(defended[-1]["train_loss"])
    assert not np.isfinite(undefended[-1]["train_loss"])
    assert undefended[-1]["test_acc"] < clean[-1]["test_acc"] - 0.1


def test_scale_drill_defended_matches_clean():
    """30% 10×-boosted uploads (model replacement): defended run within 2%
    of clean; undefended run measurably degraded."""
    clean = _run()
    defended = _run(
        attack_type="scale", attacker_ratio=0.3, attack_boost=10.0,
        federated_optimizer="FedAvg_robust", defense_type="multi_krum",
        sanitize_updates=True)
    undefended = _run(attack_type="scale", attacker_ratio=0.3,
                      attack_boost=10.0)

    assert defended[-1]["test_acc"] >= clean[-1]["test_acc"] - 0.02, (
        clean[-1]["test_acc"], defended[-1]["test_acc"])
    assert defended[-1]["test_acc"] > undefended[-1]["test_acc"] + 0.1, (
        undefended[-1]["test_acc"], defended[-1]["test_acc"])


def test_watchdog_rollback_simulator():
    """With the in-step sanitizer's threshold suppressed, only the loss
    watchdog can catch a 50×-boosted cohort: it must roll back, re-run
    without the implicated clients, and keep the run finite."""
    hist = _run(
        attack_type="scale", attacker_ratio=0.2, attack_boost=50.0,
        comm_round=8, watchdog_factor=1.5, watchdog_window=3,
        max_rollbacks=3, sanitize_z_thresh=1e6, rollback_z_thresh=3.0)

    assert any(h["rollbacks"] > 0 for h in hist)
    for h in hist:
        if h["rollbacks"]:
            # a rolled-back round re-ran with the excluded clients recorded
            assert h["quarantined"], h
        assert np.isfinite(h["train_loss"]), h
    assert np.isfinite(hist[-1]["test_acc"])


def test_defenses_disabled_history_unchanged():
    """No defense knobs -> no self-healing keys in the round history (the
    disabled path must stay byte-identical to a plain run)."""
    hist = _run(comm_round=4)
    for h in hist:
        assert "quarantined" not in h and "rollbacks" not in h, h


# --- deterministic corruption plumbing ---------------------------------------


def test_corrupt_update_tree_kinds_and_determinism():
    tree = {"w": np.ones((3, 4), np.float32), "n": np.arange(3)}
    scaled = corrupt_update_tree(tree, "scale", scale=5.0)
    np.testing.assert_allclose(scaled["w"], 5.0)
    flipped = corrupt_update_tree(tree, "sign_flip")
    np.testing.assert_allclose(flipped["w"], -1.0)
    nanned = corrupt_update_tree(tree, "nan")
    assert np.isnan(nanned["w"]).all()
    # integer leaves cannot hold NaN — they pass through
    np.testing.assert_array_equal(np.asarray(nanned["n"]), np.arange(3))
    g1 = corrupt_update_tree(tree, "gauss", std=1.0, seed=3, token="2:5")
    g2 = corrupt_update_tree(tree, "gauss", std=1.0, seed=3, token="2:5")
    g3 = corrupt_update_tree(tree, "gauss", std=1.0, seed=3, token="2:6")
    np.testing.assert_array_equal(np.asarray(g1["w"]), np.asarray(g2["w"]))
    assert not np.allclose(np.asarray(g1["w"]), np.asarray(g3["w"]))
    with pytest.raises(ValueError):
        corrupt_update_tree(tree, "label_flip")


def test_fault_plan_byzantine_config_and_scoping():
    class A:
        fault_seed = 11
        fault_byzantine_kind = "scale"
        fault_byzantine_ranks = [2, 3]
        fault_byzantine_rounds = [1, 3]

    plan = FaultPlan.from_args(A())
    assert plan is not None and plan.active
    assert plan.byzantine_ranks == frozenset({2, 3})

    from fedml_tpu.comm import Message

    def upload(sender, rnd):
        m = Message(3, sender, 0)
        m.add_params("round_idx", rnd)
        return m

    assert not plan.should_corrupt(upload(2, 0))   # before the window
    assert plan.should_corrupt(upload(2, 1))
    assert plan.should_corrupt(upload(3, 2))
    assert not plan.should_corrupt(upload(2, 3))   # window is [start, stop)
    assert not plan.should_corrupt(upload(1, 1))   # not a byzantine rank
    with pytest.raises(ValueError):
        FaultPlan(byzantine_kind="bogus")


# --- cross-silo drills (comm-plane corruption, real round FSM) ---------------


@pytest.fixture()
def _telemetry_on():
    telemetry.configure(enabled=True, reset=True)
    yield
    telemetry.configure(enabled=True, reset=True)


@pytest.mark.chaos
def test_cross_silo_byzantine_nan_drill(_telemetry_on):
    """A silo uploading NaN deltas every round: the sanitizer quarantines it
    in-step, the run closes every round, and the global model stays finite."""
    from fedml_tpu.cross_silo.chaos import run_chaos_drill

    r = run_chaos_drill(
        fault_byzantine_kind="nan", fault_byzantine_ranks=[2],
        sanitize_updates=True, fault_drop_rate=0.0,
        local_test_on_all_clients=True, comm_round=3,
        client_num_in_total=4, client_num_per_round=4,
        # no messages vanish here, so the per-round quarantine assertions
        # need every upload — don't let the 2s straggler default close a
        # compile-heavy round 0 early on a loaded machine
        round_timeout=30.0)
    assert r.ok, r.summary()
    assert r.quarantined >= 3, r.summary()
    assert r.rollbacks == 0, r.summary()
    for h in r.history:
        assert h["quarantined"] == [2], h
        assert np.isfinite(h["local_train_loss"]), h


@pytest.mark.chaos
def test_cross_silo_watchdog_rollback(_telemetry_on):
    """Clean rounds build the loss baseline; a 1000×-scaled silo appears at
    round 3 with the in-step sanitizer threshold suppressed — the watchdog
    must spike-detect, restore the pre-aggregate params, and re-run the round
    without that silo."""
    from fedml_tpu.cross_silo.chaos import run_chaos_drill

    r = run_chaos_drill(
        fault_byzantine_kind="scale", fault_byzantine_scale=1000.0,
        fault_byzantine_ranks=[2], fault_byzantine_rounds=[3, 5],
        watchdog_factor=1.5, sanitize_z_thresh=1e6, rollback_z_thresh=3.0,
        max_rollbacks=2, fault_drop_rate=0.0, comm_round=5,
        client_num_in_total=4, client_num_per_round=4,
        local_test_on_all_clients=True, round_timeout=5.0)
    assert r.ok, r.summary()
    assert r.rollbacks >= 1, r.summary()
    by_round = {h["round"]: h for h in r.history}
    assert by_round[3]["rollbacks"] >= 1 and by_round[3]["quarantined"] == [2]
    for h in r.history:
        assert np.isfinite(h["local_train_loss"]), h
    # the healed rounds keep converging instead of absorbing the 1000x update
    assert by_round[4]["local_train_loss"] < by_round[0]["local_train_loss"]
