"""Resilience plane: retry engine, error taxonomy, seeded fault plans, and
the per-backend failure-context satellites (grpc context, mqtt_s3 orphan
blob, observer isolation, round-state store)."""

import threading
import time
from types import SimpleNamespace

import numpy as np
import pytest

from fedml_tpu.comm import LoopbackHub, Message
from fedml_tpu.comm.loopback import LoopbackCommManager
from fedml_tpu.comm.resilience import (
    DEFAULT_RETRY_POLICY,
    FaultPlan,
    FaultRule,
    FaultyCommManager,
    LeaseTable,
    NetworkPartition,
    RetryPolicy,
    SendFailure,
    TransientSendError,
    is_retryable,
    retry_send,
)
from fedml_tpu.core import telemetry

FAST = RetryPolicy(max_retries=2, base_delay_s=0.001, max_delay_s=0.002)


@pytest.fixture(autouse=True)
def _fresh_telemetry():
    telemetry.configure(enabled=True, reset=True)
    yield
    telemetry.configure(enabled=True, reset=True)


def _counters():
    return telemetry.get_registry().snapshot()["counters"]


def _msg(mtype=3, sender=1, receiver=0, round_idx=None):
    m = Message(mtype, sender, receiver)
    if round_idx is not None:
        m.add_params("round_idx", round_idx)
    return m


# --- retry engine ------------------------------------------------------------


def test_retry_policy_delay_deterministic_and_bounded():
    p = RetryPolicy(base_delay_s=0.1, max_delay_s=1.0, backoff=2.0, jitter=0.5)
    for attempt in range(6):
        d1 = p.delay(attempt, key="a:1")
        d2 = p.delay(attempt, key="a:1")
        assert d1 == d2  # hash-derived jitter, not wall-clock randomness
        nominal = min(0.1 * 2.0 ** attempt, 1.0)
        assert 0.5 * nominal <= d1 <= 1.5 * nominal
    # different keys decorrelate
    assert p.delay(0, key="a:1") != p.delay(0, key="b:2")


def test_retry_policy_from_args():
    args = SimpleNamespace(send_retries=5, send_retry_base_s=0.01,
                           send_retry_max_s=0.5, send_retry_backoff=3.0,
                           send_retry_jitter=0.0)
    p = RetryPolicy.from_args(args)
    assert (p.max_retries, p.base_delay_s, p.max_delay_s, p.backoff,
            p.jitter) == (5, 0.01, 0.5, 3.0, 0.0)
    assert RetryPolicy.from_args(None) is DEFAULT_RETRY_POLICY


def test_retry_send_transient_then_success_returns_value():
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise TransientSendError("blip")
        return "mem://the-url"

    out = retry_send(flaky, policy=FAST, backend="testbk", receiver_id=4)
    assert out == "mem://the-url"
    assert len(calls) == 3
    assert _counters().get("fedml_send_retries_total{backend=testbk}") == 2
    assert "fedml_send_failures_total{backend=testbk}" not in _counters()


def test_retry_send_fatal_error_does_not_retry():
    calls = []

    def doomed():
        calls.append(1)
        raise FileNotFoundError("/nonexistent/model")

    with pytest.raises(SendFailure) as ei:
        retry_send(doomed, policy=FAST, backend="testbk", receiver_id=2)
    assert len(calls) == 1  # fatal: no second attempt
    assert ei.value.attempts == 1
    assert "fatal error" in str(ei.value)
    assert _counters().get("fedml_send_failures_total{backend=testbk}") == 1


def test_retry_send_budget_exhausted_raises_with_context():
    def always_down():
        raise ConnectionError("peer rebooting")

    with pytest.raises(SendFailure) as ei:
        retry_send(always_down, policy=FAST, backend="testbk",
                   receiver_id=7, describe="rank 0 -> 10.0.0.7:9897")
    exc = ei.value
    assert exc.attempts == FAST.max_retries + 1
    assert exc.receiver_id == 7
    assert exc.backend == "testbk"
    assert "rank 7" in str(exc)
    assert "10.0.0.7:9897" in str(exc)
    assert (_counters().get("fedml_send_retries_total{backend=testbk}")
            == FAST.max_retries)


def test_is_retryable_taxonomy():
    assert is_retryable(TransientSendError("x"))
    assert is_retryable(ConnectionError("reset"))
    assert is_retryable(TimeoutError("slow"))
    assert is_retryable(OSError("socket"))
    # a spent budget never re-wraps; local misconfiguration never retries
    assert not is_retryable(SendFailure("done"))
    assert not is_retryable(FileNotFoundError("gone"))
    assert not is_retryable(PermissionError("wall"))
    assert not is_retryable(ValueError("codec bug"))


def test_is_retryable_grpc_codes():
    grpc = pytest.importorskip("grpc")

    class _Rpc(grpc.RpcError):
        def __init__(self, code):
            self._code = code

        def code(self):
            return self._code

    assert is_retryable(_Rpc(grpc.StatusCode.UNAVAILABLE))
    assert is_retryable(_Rpc(grpc.StatusCode.DEADLINE_EXCEEDED))
    assert not is_retryable(_Rpc(grpc.StatusCode.INVALID_ARGUMENT))
    assert not is_retryable(_Rpc(grpc.StatusCode.UNIMPLEMENTED))


# --- fault plan --------------------------------------------------------------


def test_fault_plan_deterministic_across_interleavings():
    """Same seed must make the same calls per edge regardless of how sends
    from different edges interleave globally."""
    rules = (FaultRule("drop", 0.5), FaultRule("duplicate", 0.3))

    def decide_all(order):
        plan = FaultPlan(seed=3, rules=rules)
        out = {"e1": [], "e2": []}
        for edge in order:
            sender = 1 if edge == "e1" else 2
            d = plan.decide(_msg(3, sender, 0))
            out[edge].append((d.drop, d.duplicate))
        return out

    a = decide_all(["e1", "e1", "e2", "e1", "e2"] * 20)
    b = decide_all(["e2", "e1", "e2", "e1", "e1"] * 20)  # different global order
    assert a == b
    # at 50% drop over 60 draws, both outcomes must appear
    assert any(drop for drop, _ in a["e1"]) and not all(drop for drop, _ in a["e1"])
    # a different seed reshuffles the plan
    plan2 = FaultPlan(seed=4, rules=rules)
    c = [plan2.decide(_msg(3, 1, 0)).drop for _ in range(60)]
    assert c != [drop for drop, _ in a["e1"]]


def test_fault_rule_scoping_by_type_and_round():
    rule = FaultRule("drop", 1.0, msg_types=frozenset({3}), rounds=(1, 3))
    assert rule.matches(3, 1)
    assert rule.matches(3, 2)
    assert not rule.matches(3, 0)
    assert not rule.matches(3, 3)  # [start, stop)
    assert not rule.matches(2, 1)  # wrong type
    assert not rule.matches(3, None)  # round-scoped rules skip round-less traffic
    plan = FaultPlan(seed=0, rules=(rule,))
    assert not plan.decide(_msg(3, 1, 0)).drop  # no round param
    assert plan.decide(_msg(3, 1, 0, round_idx=1)).drop


def test_fault_plan_from_args_disabled_means_none():
    assert FaultPlan.from_args(None) is None
    assert FaultPlan.from_args(SimpleNamespace()) is None
    # a seed alone configures nothing
    assert FaultPlan.from_args(SimpleNamespace(fault_seed=9)) is None
    # zero rates configure nothing (the byte-parity contract)
    assert FaultPlan.from_args(SimpleNamespace(
        fault_seed=9, fault_drop_rate=0.0, fault_duplicate_rate=0.0)) is None
    plan = FaultPlan.from_args(SimpleNamespace(fault_seed=9, fault_drop_rate=0.2))
    assert plan is not None and plan.active and plan.seed == 9
    assert [r.action for r in plan.rules] == ["drop"]
    # crash config alone activates; crash round defaults to 1
    plan = FaultPlan.from_args(SimpleNamespace(fault_crash_rank=2))
    assert plan is not None and plan.crash_rank == 2 and plan.crash_at_round == 1
    assert plan.should_crash(2, 1) and not plan.should_crash(2, 0)
    assert not plan.should_crash(1, 5)


# --- chaos wrapper over a real backend ---------------------------------------


def _wrapped_sender(plan, rank=1, size=2):
    hub = LoopbackHub()
    inner = LoopbackCommManager(rank=rank, size=size, hub=hub,
                                retry_policy=FAST)
    return hub, FaultyCommManager(inner, plan, rank=rank, retry_policy=FAST)


def test_faulty_wrapper_drops_matching_messages():
    plan = FaultPlan(seed=0, rules=(FaultRule("drop", 1.0, msg_types=frozenset({3})),))
    hub, mgr = _wrapped_sender(plan)
    mgr.send_message(_msg(3, 1, 0))
    assert hub.register(0).qsize() == 0  # dropped on the floor
    mgr.send_message(_msg(5, 1, 0))  # other types pass through
    assert hub.register(0).qsize() == 1
    assert _counters().get("fedml_faults_injected_total{action=drop}") == 1


def test_faulty_wrapper_duplicates_messages():
    plan = FaultPlan(seed=0, rules=(FaultRule("duplicate", 1.0),))
    hub, mgr = _wrapped_sender(plan)
    mgr.send_message(_msg(3, 1, 0))
    assert hub.register(0).qsize() == 2
    assert _counters().get("fedml_faults_injected_total{action=duplicate}") == 1


def test_faulty_wrapper_injected_failures_exhaust_retry_budget():
    plan = FaultPlan(seed=0, rules=(FaultRule("fail_send", 1.0),))
    hub, mgr = _wrapped_sender(plan)
    with pytest.raises(SendFailure) as ei:
        mgr.send_message(_msg(3, 1, 0))
    assert ei.value.attempts == FAST.max_retries + 1
    assert hub.register(0).qsize() == 0  # every attempt failed before the wire
    assert (_counters().get("fedml_faults_injected_total{action=fail_send}")
            == FAST.max_retries + 1)


def test_faulty_wrapper_crash_blackholes_both_directions():
    plan = FaultPlan(seed=0, crash_rank=1, crash_at_round=1)
    hub, mgr = _wrapped_sender(plan)
    seen = []
    mgr.add_observer(SimpleNamespace(
        receive_message=lambda t, m: seen.append(m.get_type())))

    mgr.send_message(_msg(3, 1, 0, round_idx=0))  # before the crash round
    assert hub.register(0).qsize() == 1
    mgr.receive_message(2, _msg(2, 0, 1, round_idx=0))
    assert seen == [2]

    mgr.receive_message(2, _msg(2, 0, 1, round_idx=1))  # crash trigger
    assert mgr.crashed
    assert seen == [2]  # the crashing message never reaches the actor
    # process death stopped the inner receive loop (poison pill posted)
    assert hub.register(1).get_nowait() is None
    mgr.send_message(_msg(3, 1, 0, round_idx=1))  # a dead process sends nothing
    assert hub.register(0).qsize() == 1
    assert _counters().get("fedml_faults_injected_total{action=crash}") == 1


# --- network partitions (tiered-federation satellite) -------------------------


def test_network_partition_key_is_canonical():
    a = NetworkPartition(frozenset({0}), frozenset({1, 2}), rounds=(1, 3))
    b = NetworkPartition(frozenset({1, 2}), frozenset({0}), rounds=(1, 3))
    assert a.key == b.key  # which side is "A" is not part of the identity
    c = NetworkPartition(frozenset({0}), frozenset({1, 2}), rounds=(2, 4))
    assert c.key != a.key  # the round window is


def test_network_partition_overlapping_sides_rejected():
    with pytest.raises(ValueError):
        NetworkPartition(frozenset({0, 1}), frozenset({1, 2}))


def test_network_partition_window_is_half_open():
    p = NetworkPartition(frozenset({0}), frozenset({1}), rounds=(1, 3))
    assert not p.in_window(0)
    assert p.in_window(1) and p.in_window(2)
    assert not p.in_window(3)  # [start, stop)
    assert not p.in_window(None)  # round-less traffic skips a windowed cut
    assert NetworkPartition(frozenset({0}), frozenset({1})).in_window(None)


def test_partition_drops_only_cut_crossing_traffic():
    plan = FaultPlan(seed=0, partition=NetworkPartition(
        frozenset({0, 2}), frozenset({1})))
    assert plan.active
    assert plan.should_partition(_msg(3, 1, 0))
    assert plan.should_partition(_msg(3, 0, 1))  # both directions
    assert not plan.should_partition(_msg(3, 2, 0))  # same side passes


def test_partition_round_hint_unsticks_stale_stamps():
    """A cut-off peer keeps stamping its last-known round; the receiver
    judges the window against max(stamp, its own clock), so the cut holds
    while the window is open and heals the moment the receiver's clock
    leaves it."""
    plan = FaultPlan(seed=0, partition=NetworkPartition(
        frozenset({0}), frozenset({1}), rounds=(1, 3)))
    stale = _msg(3, 1, 0, round_idx=1)
    assert plan.should_partition(stale, round_hint=2)   # clock still inside
    assert not plan.should_partition(stale, round_hint=3)  # healed
    # the hint alone drives round-less traffic (heartbeats) into the window
    assert plan.should_partition(_msg(3, 1, 0), round_hint=1)
    assert not plan.should_partition(_msg(3, 1, 0), round_hint=0)


def test_flaky_partition_replays_identically():
    def draws(seed):
        plan = FaultPlan(seed=seed, partition=NetworkPartition(
            frozenset({0}), frozenset({1}), rate=0.5))
        return [plan.should_partition(_msg(3, 1, 0)) for _ in range(80)]

    a = draws(7)
    assert a == draws(7)  # sha256-derived: bit-identical replay
    assert any(a) and not all(a)  # lossy, not absolute
    assert draws(8) != a  # a different seed reshuffles the cut


def test_partition_sequence_space_isolated_from_wire_faults():
    """Adding a partition must not reshuffle the wire-fault draws — each
    consumes its own per-edge sequence space."""
    rules = (FaultRule("drop", 0.5),)
    with_cut = FaultPlan(seed=7, rules=rules, partition=NetworkPartition(
        frozenset({5}), frozenset({6}), rate=0.5))
    without = FaultPlan(seed=7, rules=rules)
    a, b = [], []
    for _ in range(40):
        a.append(with_cut.decide(_msg(3, 1, 0)).drop)
        with_cut.should_partition(_msg(3, 5, 6))  # burns only part: sequence
        b.append(without.decide(_msg(3, 1, 0)).drop)
    assert a == b


def test_fault_plan_from_args_partition():
    plan = FaultPlan.from_args(SimpleNamespace(
        fault_partition_ranks_a=[0], fault_partition_ranks_b=[1, 2],
        fault_partition_rounds=(1, 2)))
    assert plan is not None and plan.active
    assert plan.partition.ranks_a == frozenset({0})
    assert plan.partition.ranks_b == frozenset({1, 2})
    assert plan.partition.rounds == (1, 2) and plan.partition.rate == 1.0
    # one side alone configures nothing (the byte-parity contract)
    assert FaultPlan.from_args(
        SimpleNamespace(fault_partition_ranks_a=[0])) is None


@pytest.mark.parametrize("backend", ["loopback", "grpc", "trpc", "mqtt_s3"])
def test_partition_composes_with_wrapper_on_every_backend(backend):
    """The windowed cut drops crossing traffic at the wrapped RECEIVER on
    every transport, and heals once the receiver's round clock leaves the
    window — even for a stale-stamped straggler."""
    if backend == "loopback":
        hub = LoopbackHub()
        inner = LoopbackCommManager(rank=0, size=2, hub=hub)
        sender = LoopbackCommManager(rank=1, size=2, hub=hub)
    elif backend == "grpc":
        from fedml_tpu.comm.grpc_backend import GRPCCommManager

        inner = GRPCCommManager(rank=0, size=2, base_port=26890)
        sender = GRPCCommManager(rank=1, size=2, base_port=26890)
    elif backend == "trpc":
        from fedml_tpu.comm.trpc_backend import TRPCCommManager

        inner = TRPCCommManager(rank=0, size=2, base_port=26990)
        sender = TRPCCommManager(rank=1, size=2, base_port=26990)
    else:
        from fedml_tpu.comm import (InMemoryBlobStore, InProcessBroker,
                                    MqttS3CommManager)

        broker, store = InProcessBroker(), InMemoryBlobStore()
        inner = MqttS3CommManager(broker, store, rank=0, size=2)
        sender = MqttS3CommManager(broker, store, rank=1, size=2)

    plan = FaultPlan(seed=0, partition=NetworkPartition(
        frozenset({0}), frozenset({1}), rounds=(1, 3)))
    mgr = FaultyCommManager(inner, plan, rank=0, retry_policy=FAST)
    got = []
    mgr.add_observer(SimpleNamespace(
        receive_message=lambda t, m: got.append(m.get("round_idx"))))
    loop = threading.Thread(target=mgr.handle_receive_message, daemon=True)
    loop.start()
    try:
        sender.send_message(_msg(3, 1, 0, round_idx=0))  # pre-window
        sender.send_message(_msg(3, 1, 0, round_idx=1))  # cut
        sender.send_message(_msg(3, 1, 0, round_idx=2))  # cut
        sender.send_message(_msg(3, 1, 0, round_idx=3))  # window closed
        # stale straggler: the receiver's clock is already at 3, so the cut
        # stays healed for a round-1 stamp
        sender.send_message(_msg(3, 1, 0, round_idx=1))
        deadline = time.time() + 10
        while len(got) < 3 and time.time() < deadline:
            time.sleep(0.01)
        assert got == [0, 3, 1]
        assert _counters().get(
            "fedml_faults_injected_total{action=partition}") == 2
    finally:
        mgr.stop_receive_message()
        sender.stop_receive_message()
        loop.join(timeout=5)


# --- lease table (tiered-federation tentpole) ---------------------------------


def test_lease_table_expiry_renewal_and_drop():
    now = [0.0]
    lt = LeaseTable(ttl_s=1.0, clock=lambda: now[0])
    lt.renew(1)
    lt.renew(2)
    assert lt.live() == (1, 2) and lt.expired() == ()
    assert lt.holds(1)
    now[0] = 0.9
    lt.renew(2)
    now[0] = 1.5
    assert lt.live() == (2,)  # 1's lease lapsed, 2's was renewed in time
    assert lt.expired() == (1,)
    assert not lt.holds(1) and lt.holds(2)
    # expired() leaves the verdict to the caller: a late heartbeat re-admits
    lt.renew(1)
    assert lt.expired() == () and lt.live() == (1, 2)
    lt.drop(1)
    assert lt.live() == (2,) and not lt.holds(1)


# --- observer isolation (satellite) ------------------------------------------


def test_observer_exception_does_not_kill_receive_loop():
    hub = LoopbackHub()
    mgr = LoopbackCommManager(rank=0, size=2, hub=hub)

    class Bad:
        def receive_message(self, t, m):
            raise RuntimeError("handler bug")

    good = []
    mgr.add_observer(Bad())
    mgr.add_observer(SimpleNamespace(
        receive_message=lambda t, m: good.append(m.get_type())))

    for mtype in (3, 5):
        m = _msg(mtype, 1, 0)
        hub.post(0, m.to_bytes())
    hub.post(0, None)

    rx = threading.Thread(target=mgr.handle_receive_message, daemon=True)
    rx.start()
    rx.join(timeout=10)
    assert not rx.is_alive()
    # the bad observer raised on both messages; the loop kept draining and
    # the good observer saw everything
    assert good == [3, 5]
    errs = [v for k, v in _counters().items()
            if k.startswith("fedml_observer_errors_total")]
    assert sum(errs) == 2


# --- mqtt_s3 orphan blob (satellite) -----------------------------------------


def test_mqtt_s3_deletes_orphaned_blob_when_publish_fails():
    from fedml_tpu.comm.mqtt_s3 import MqttS3CommManager
    from fedml_tpu.comm.pubsub import InProcessBroker
    from fedml_tpu.comm.store import InMemoryBlobStore

    class DeadBroker(InProcessBroker):
        def publish(self, topic, payload):
            raise ConnectionError("broker unreachable")

    store = InMemoryBlobStore()
    mgr = MqttS3CommManager(DeadBroker(), store, rank=0, size=2,
                            retry_policy=FAST)
    msg = _msg(2, 0, 1)
    # big enough to force the store-offload path (> INLINE_PAYLOAD_MAX_BYTES)
    msg.add_params(Message.MSG_ARG_KEY_MODEL_PARAMS,
                   {"w": np.zeros(4096, dtype=np.float64)})
    with pytest.raises(SendFailure):
        mgr.send_message(msg)
    # the blob was uploaded before the publish failed; nobody will ever learn
    # its key, so it must have been deleted again
    assert store.list_keys() == []


def test_mqtt_s3_inline_send_survives_transient_broker():
    from fedml_tpu.comm.mqtt_s3 import MqttS3CommManager
    from fedml_tpu.comm.pubsub import InProcessBroker
    from fedml_tpu.comm.store import InMemoryBlobStore

    class FlakyBroker(InProcessBroker):
        def __init__(self):
            super().__init__()
            self.fails = 2

        def publish(self, topic, payload):
            if self.fails > 0:
                self.fails -= 1
                raise ConnectionError("blip")
            super().publish(topic, payload)

    got = []
    broker = FlakyBroker()
    server = MqttS3CommManager(broker, InMemoryBlobStore(), rank=0, size=2,
                               retry_policy=FAST)
    server.add_observer(SimpleNamespace(
        receive_message=lambda t, m: got.append(t)))
    client = MqttS3CommManager(broker, InMemoryBlobStore(), rank=1, size=2,
                               retry_policy=FAST)
    client.send_message(_msg(5, 1, 0))
    server._inbox.put(None)
    server.handle_receive_message()
    assert got == [5]
    assert _counters().get("fedml_send_retries_total{backend=mqtt_s3}") == 2


# --- grpc failure context (satellite) ----------------------------------------


def test_grpc_send_failure_names_rank_and_dialed_target():
    pytest.importorskip("grpc")
    from fedml_tpu.comm.grpc_backend import GRPCCommManager

    mgr = GRPCCommManager(rank=0, size=2, ip_config={0: "127.0.0.1"},
                          base_port=19340, retry_policy=FAST)
    try:
        with pytest.raises(SendFailure) as ei:
            mgr.send_message(_msg(2, 0, 1))
        text = str(ei.value)
        assert "rank 0 ->" in text  # the sending rank
        assert "no ip-table entry for rank 1" in text  # the dial target
        assert ei.value.backend == "grpc"
        assert ei.value.receiver_id == 1
    finally:
        mgr.stop_receive_message()


# --- round-state store -------------------------------------------------------


def test_round_state_store_roundtrip_restores_params_and_rng(tmp_path):
    from fedml_tpu.utils.checkpoint import RoundStateStore

    store = RoundStateStore(str(tmp_path / "round_state.msgpack"))
    assert not store.exists()
    params = {"w": np.arange(12, dtype=np.float32).reshape(3, 4),
              "b": np.float64(0.5)}
    np.random.seed(123)
    store.save(7, params)
    expected_draw = np.random.rand(4)  # what a never-crashed server draws next
    np.random.seed(999)  # the "restarted process" has unrelated RNG state

    state = RoundStateStore(store.path).load()
    assert store.exists()
    assert state["round_idx"] == 7
    np.testing.assert_array_equal(state["params"]["w"], params["w"])
    assert float(state["params"]["b"]) == 0.5
    # RNG was re-seated: post-resume draws match the uninterrupted run
    np.testing.assert_array_equal(np.random.rand(4), expected_draw)


def test_round_state_store_crash_mid_save_preserves_previous_state(
        tmp_path, monkeypatch):
    """A crash between the temp-file write and the atomic rename must leave
    the previous round's state fully loadable (the whole point of the
    tmp + os.replace protocol)."""
    import os

    from fedml_tpu.utils.checkpoint import RoundStateStore

    store = RoundStateStore(str(tmp_path / "round_state.msgpack"))
    p1 = {"w": np.ones(3, dtype=np.float32)}
    store.save(1, p1)

    real_replace = os.replace

    def crash_replace(src, dst):
        raise OSError("simulated power cut before rename")

    monkeypatch.setattr(os, "replace", crash_replace)
    with pytest.raises(OSError):
        store.save(2, {"w": np.zeros(3, dtype=np.float32)})
    monkeypatch.setattr(os, "replace", real_replace)

    state = RoundStateStore(store.path).load(restore_rng=False)
    assert state["round_idx"] == 1
    np.testing.assert_array_equal(state["params"]["w"], p1["w"])
    # and a post-crash save still goes through cleanly over the leftovers
    store.save(2, {"w": np.full(3, 2.0, dtype=np.float32)})
    assert RoundStateStore(store.path).load(
        restore_rng=False)["round_idx"] == 2


@pytest.mark.skipif(not hasattr(__import__("os"), "O_DIRECTORY"),
                    reason="directory fsync is POSIX-only")
def test_round_state_store_save_fsyncs_parent_directory(tmp_path, monkeypatch):
    """fsync on the temp file only persists the data blocks; the rename
    itself lives in the parent directory entry, which needs its own fsync to
    survive a power cut."""
    import os
    import stat

    from fedml_tpu.utils.checkpoint import RoundStateStore

    synced_dirs = []
    real_fsync = os.fsync

    def recording_fsync(fd):
        if stat.S_ISDIR(os.fstat(fd).st_mode):
            synced_dirs.append(True)
        return real_fsync(fd)

    monkeypatch.setattr(os, "fsync", recording_fsync)
    store = RoundStateStore(str(tmp_path / "sub" / "round_state.msgpack"))
    store.save(3, {"w": np.ones(2, dtype=np.float32)})
    assert synced_dirs, "save() never fsynced the parent directory"


def test_concurrent_retry_send_jitter_stays_per_edge_deterministic(
        monkeypatch):
    """Two threads retrying on one shared backend must each see exactly the
    delay sequence the pure per-edge hash jitter prescribes — thread
    interleaving must not bleed one edge's backoff into the other's."""
    import fedml_tpu.comm.resilience as res

    policy = RetryPolicy(max_retries=3, base_delay_s=0.001, max_delay_s=0.1)
    recorded = {}  # thread ident -> [delay, ...]
    rec_lock = threading.Lock()

    def recording_sleep(dt):
        with rec_lock:
            recorded.setdefault(threading.get_ident(), []).append(dt)

    monkeypatch.setattr(res.time, "sleep", recording_sleep)
    barrier = threading.Barrier(2)
    idents = {}

    def edge(receiver_id):
        fails = [0]

        def flaky():
            barrier.wait(timeout=5.0)  # maximize interleaving pressure
            if fails[0] < policy.max_retries:
                fails[0] += 1
                raise TransientSendError("blip")
            return "ok"

        idents[receiver_id] = threading.get_ident()
        retry_send(flaky, policy=policy, backend="shared",
                   receiver_id=receiver_id)

    threads = [threading.Thread(target=edge, args=(rid,))
               for rid in (11, 22)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30.0)
        assert not t.is_alive()

    for rid in (11, 22):
        oracle = [policy.delay(a, key=f"shared:{rid}")
                  for a in range(policy.max_retries)]
        assert recorded[idents[rid]] == oracle
    # the jitter is per-edge: distinct receivers draw distinct sequences
    assert recorded[idents[11]] != recorded[idents[22]]
