"""WAN plane: blob store, pub/sub brokers, MQTT_S3 backend, cross-silo e2e."""

import os
import threading
import time

import numpy as np
import pytest

import fedml_tpu
from fedml_tpu.comm import (
    FileSystemBlobStore,
    FileSystemBroker,
    InMemoryBlobStore,
    InProcessBroker,
    Message,
    MqttS3CommManager,
)


def test_filesystem_blob_store_roundtrip(tmp_path):
    store = FileSystemBlobStore(root=str(tmp_path))
    url = store.put("topic_abc/key1", b"\x00\x01weights")
    assert url.startswith("file://")
    assert store.get("topic_abc/key1") == b"\x00\x01weights"
    assert store.list_keys("topic_abc") == ["topic_abc_key1"]
    store.delete("topic_abc/key1")
    assert store.list_keys() == []
    store.delete("topic_abc/key1")  # idempotent


def test_filesystem_broker_order_and_isolation(tmp_path):
    broker = FileSystemBroker(root=str(tmp_path))
    got_a, got_b = [], []
    broker.subscribe("alpha", lambda t, p: got_a.append(p))
    broker.subscribe("beta", lambda t, p: got_b.append(p))
    for i in range(5):
        broker.publish("alpha", f"a{i}".encode())
    broker.publish("beta", b"b0")
    deadline = time.time() + 5
    while (len(got_a), len(got_b)) != (5, 1) and time.time() < deadline:
        time.sleep(0.01)
    assert got_a == [f"a{i}".encode() for i in range(5)]  # in publish order
    assert got_b == [b"b0"]
    broker.close()


def test_filesystem_broker_no_history_replay(tmp_path):
    broker = FileSystemBroker(root=str(tmp_path))
    broker.publish("t", b"old")
    got = []
    broker.subscribe("t", lambda t, p: got.append(p))  # subscribes at head
    broker.publish("t", b"new")
    deadline = time.time() + 5
    while not got and time.time() < deadline:
        time.sleep(0.01)
    assert got == [b"new"]  # MQTT semantics: no replay
    got2 = []
    broker.subscribe_from_start("t", lambda t, p: got2.append(p))
    deadline = time.time() + 5
    while len(got2) < 2 and time.time() < deadline:
        time.sleep(0.01)
    assert got2 == [b"old", b"new"]  # job-queue semantics: full replay
    broker.close()


def test_mqtt_s3_payload_rides_the_store():
    """Large model params must be replaced by key+URL in the control message
    and transparently restored on receive (reference
    mqtt_s3_multi_clients_comm_manager.py:233-327 semantics)."""
    broker = InProcessBroker()
    store = InMemoryBlobStore()
    server = MqttS3CommManager(broker, store, rank=0, size=2, run_id="run7")

    received = []

    class Obs:
        def receive_message(self, t, msg):
            received.append(msg)
            server.stop_receive_message()

    server.add_observer(Obs())
    client = MqttS3CommManager(broker, store, rank=1, size=2, run_id="run7")

    big = {"w": np.arange(10_000, dtype=np.float32)}
    msg = Message(type=3, sender_id=1, receiver_id=0)
    msg.add_params(Message.MSG_ARG_KEY_MODEL_PARAMS, big)
    client.send_message(msg)
    server.handle_receive_message()

    assert len(received) == 1
    got = received[0].get(Message.MSG_ARG_KEY_MODEL_PARAMS)
    np.testing.assert_array_equal(got["w"], big["w"])
    # the blob really went through the store, and the control message carried
    # the locator
    assert len(store.list_keys()) == 1
    assert received[0].get(Message.MSG_ARG_KEY_MODEL_PARAMS_URL, "").startswith("mem://")


def test_mqtt_s3_small_payload_inline():
    broker = InProcessBroker()
    store = InMemoryBlobStore()
    server = MqttS3CommManager(broker, store, rank=0, size=2)
    got = []

    class Obs:
        def receive_message(self, t, msg):
            got.append(msg)
            server.stop_receive_message()

    server.add_observer(Obs())
    client = MqttS3CommManager(broker, store, rank=1, size=2)
    msg = Message(type=4, sender_id=1, receiver_id=0)
    msg.add_params(Message.MSG_ARG_KEY_MODEL_PARAMS, {"b": np.zeros(4, np.float32)})
    client.send_message(msg)
    server.handle_receive_message()
    assert store.list_keys() == []  # tiny payload stays inline
    np.testing.assert_array_equal(
        got[0].get(Message.MSG_ARG_KEY_MODEL_PARAMS)["b"], np.zeros(4))


def test_cross_silo_e2e_over_mqtt_s3(tmp_path):
    """Full cross-silo round protocol over the filesystem broker + store —
    the MLOps-default transport path, no paho/boto3 required."""
    from fedml_tpu.cross_silo import FedML_Horizontal

    broker_dir = str(tmp_path / "broker")
    store_dir = str(tmp_path / "blobs")
    args = fedml_tpu.init(config=dict(
        dataset="mnist", model="lr", debug_small_data=True,
        client_num_in_total=2, client_num_per_round=2, comm_round=2,
        learning_rate=0.1, batch_size=8, frequency_of_the_test=1,
        random_seed=0, run_id="e2e1",
        mqtt_broker_dir=broker_dir, blob_store_dir=store_dir,
    ))
    managers = [
        FedML_Horizontal(args, rank, 2, backend="MQTT_S3")
        for rank in range(3)
    ]
    server, clients = managers[0], managers[1:]
    threads = [threading.Thread(target=c.run, daemon=True) for c in clients]
    for t in threads:
        t.start()
    server.start()
    server.run()
    for t in threads:
        t.join(timeout=60)
    assert len(server.history) == 2
    assert server.history[-1]["test_acc"] > 0.4
    # model weights rode the blob store, not the control plane
    assert len(os.listdir(store_dir)) > 0

def test_mqtt_s3_mnn_ships_model_files(tmp_path):
    """Beehive file-shipping variant (reference mqtt_s3_mnn/remote_storage.py
    :56,76): the sender uploads a device model FILE to the store, the
    receiver re-materializes it locally and gets the local path."""
    from fedml_tpu.comm.managers import create_comm_backend
    from fedml_tpu.comm.mqtt_s3 import MSG_ARG_KEY_MODEL_FILE
    from fedml_tpu.models import build_mobile_model_file, load_mobile_model_file

    broker = FileSystemBroker(root=str(tmp_path / "broker"))
    store = FileSystemBlobStore(root=str(tmp_path / "blobs"))
    server = create_comm_backend(
        "MQTT_S3_MNN", rank=0, size=2, broker=broker, store=store,
        download_dir=str(tmp_path / "srv_dl"))
    client = create_comm_backend(
        "MQTT_S3_MNN", rank=1, size=2, broker=broker, store=store,
        download_dir=str(tmp_path / "cli_dl"))

    # server authors the device artifact and ships the FILE downlink
    art_path = str(tmp_path / "lenet5.fedml")
    build_mobile_model_file("lenet5", art_path, seed=1)
    msg = Message("init", 0, 1)
    msg.add_params(MSG_ARG_KEY_MODEL_FILE, art_path)
    server.send_message(msg)

    got = []
    class Obs:
        def receive_message(self, t, m):
            got.append(m)
    client.add_observer(Obs())
    t = threading.Thread(target=client.handle_receive_message, daemon=True)
    t.start()
    deadline = time.time() + 10
    while not got and time.time() < deadline:
        time.sleep(0.05)
    assert got, "file message never arrived"
    local = got[0].get(MSG_ARG_KEY_MODEL_FILE)
    assert local != art_path and os.path.exists(local)
    # the re-materialized artifact loads into the same model
    model, variables = load_mobile_model_file(local)
    import jax.numpy as jnp
    assert model.apply(variables, jnp.zeros((1, 28, 28, 1))).shape == (1, 10)
    client.stop_receive_message()
    server.stop_receive_message()
    broker.close()
