"""Parrot-TPU simulator: cohort sharded over an 8-device mesh must match the
SP simulator numerically (same seeds => same rounds). This is the loopback-
style parity test the reference lacks (SURVEY.md §4 lesson)."""

import jax
import numpy as np
import pytest

import fedml_tpu
from fedml_tpu.parallel import AXIS_CLIENT, MeshConfig, create_mesh
from fedml_tpu.simulation import build_simulator


def small_args(**over):
    base = dict(
        dataset="mnist", model="lr", debug_small_data=True,
        client_num_in_total=20, client_num_per_round=8, comm_round=3,
        learning_rate=0.1, epochs=1, batch_size=32,
        frequency_of_the_test=2, random_seed=0, partition_method="hetero",
        partition_alpha=0.5,
    )
    base.update(over)
    return fedml_tpu.init(config=base)


def test_mesh_matches_sp():
    args = small_args()
    sim_sp, f_sp = build_simulator(args)
    h_sp = sim_sp.run(f_sp, log_fn=None)

    mesh = create_mesh(MeshConfig(axes=((AXIS_CLIENT, 8),)))
    args2 = small_args()
    sim_tpu, f_tpu = build_simulator(args2, mesh=mesh)
    h_tpu = sim_tpu.run(f_tpu, log_fn=None)

    for a, b in zip(h_sp, h_tpu):
        assert a["train_loss"] == pytest.approx(b["train_loss"], rel=1e-4)
    assert h_sp[-1]["test_acc"] == pytest.approx(h_tpu[-1]["test_acc"], abs=0.02)


def test_mesh_params_replicated_and_finite():
    mesh = create_mesh(MeshConfig(axes=((AXIS_CLIENT, 4),)), devices=jax.devices()[:4])
    args = small_args(client_num_per_round=8, comm_round=2)
    sim, f = build_simulator(args, mesh=mesh)
    sim.run(f, log_fn=None)
    leaves = jax.tree.leaves(sim.params)
    assert all(np.isfinite(np.asarray(l)).all() for l in leaves)
