"""Hosted-MLOps agent surface + model-zoo depth (VERDICT r2 missing #4/#5).

Device/account binding and incremental remote log upload with injectable
transports (reference client_runner.py:645-666, mlops_runtime_log.py:136);
EfficientNet compound-scaling family; SyncBN via flax axis_name psum."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from fedml_tpu.core.mlops import (
    MLOpsRuntimeLogUploader,
    bind_account_and_device_id,
    get_device_id,
)


def test_get_device_id_is_stable_hex():
    d = get_device_id()
    assert d.startswith("0x") and int(d, 16) > 0
    assert d == get_device_id()


def test_bind_account_and_device_id_schema_and_outcomes():
    posts = []

    def ok_post(url, json_params, headers, ca_path=None):
        posts.append((url, json_params, headers))
        return {"code": "SUCCESS", "data": {"id": 77}}

    edge = bind_account_and_device_id(
        "https://host/bind", "acct9", http_post=ok_post)
    assert edge == 77
    url, params, headers = posts[0]
    # reference request schema (client_runner.py:666)
    assert set(params) == {"accountid", "deviceid", "type", "gpu",
                           "processor", "network"}
    assert params["accountid"] == "acct9"
    assert headers == {"Connection": "close"}

    def refused_post(url, json_params, headers, ca_path=None):
        return {"code": "FAILED"}

    assert bind_account_and_device_id(
        "https://host/bind", "acct9", http_post=refused_post) == 0


def test_log_uploader_incremental_and_replay_on_failure(tmp_path):
    log = tmp_path / "run.log"
    log.write_text("line1\nline2\n")
    shipped = []
    fail = {"on": False}

    def post(url, body, headers, ca_path=None):
        if fail["on"]:
            raise ConnectionError("outage")
        shipped.append(body)
        return {"code": "SUCCESS"}

    up = MLOpsRuntimeLogUploader(
        run_id="r1", edge_id=5, log_file_path=str(log),
        upload_url="https://host/logs", http_post=post, interval=999)
    assert up.log_upload() == 2
    assert shipped[0]["logs"] == ["line1\n", "line2\n"]
    assert shipped[0]["edge_id"] == 5 and shipped[0]["created_by"] == "5"
    assert up.log_upload() == 0  # nothing new

    with open(log, "a") as f:
        f.write("line3\n")
    fail["on"] = True
    with pytest.raises(ConnectionError):
        up.log_upload()
    assert up.log_line_index == 2  # cursor did NOT advance on failure
    fail["on"] = False
    assert up.log_upload() == 1  # outage replays, never drops
    assert shipped[-1]["logs"] == ["line3\n"]

    # rotation/truncation: a smaller file resets the cursor instead of
    # stalling forever
    log.write_text("fresh1\n")
    assert up.log_upload() == 1
    assert shipped[-1]["logs"] == ["fresh1\n"]
    # a partial line (no newline yet) waits for the next tick
    with open(log, "a") as f:
        f.write("partial")
    assert up.log_upload() == 0
    with open(log, "a") as f:
        f.write(" done\n")
    assert up.log_upload() == 1
    assert shipped[-1]["logs"] == ["partial done\n"]


def test_edge_runner_from_binding(tmp_path):
    from fedml_tpu.cli.runner import FedMLEdgeRunner
    from fedml_tpu.comm.pubsub import InProcessBroker

    def post(url, body, headers, ca_path=None):
        return {"code": "SUCCESS", "data": {"id": 42}}

    runner = FedMLEdgeRunner.from_binding(
        InProcessBroker(), "https://host/bind", "acct", http_post=post,
        home_dir=str(tmp_path))
    assert runner.edge_id == 42
    runner.stop()

    def refuse(url, body, headers, ca_path=None):
        return {"code": "NO"}

    with pytest.raises(RuntimeError, match="binding refused"):
        FedMLEdgeRunner.from_binding(
            InProcessBroker(), "https://host/bind", "acct",
            http_post=refuse, home_dir=str(tmp_path))


# --- model-zoo depth -------------------------------------------------------

def test_efficientnet_family_scales():
    from fedml_tpu.models import EfficientNet, create

    x = jnp.zeros((1, 32, 32, 3), jnp.float32)
    sizes = {}
    for variant in ("b0", "b2"):
        m = EfficientNet(num_classes=10, variant=variant)
        v = m.init(jax.random.PRNGKey(0), x, train=False)
        out = m.apply(v, x, train=False)
        assert out.shape == (1, 10)
        sizes[variant] = sum(a.size for a in jax.tree.leaves(v))
    assert sizes["b2"] > sizes["b0"]  # compound scaling grows the net

    class A:  # factory dispatch
        model = "efficientnet-b1"
        dataset = "cifar10"

    m = create(A(), 10)
    assert m.variant == "b1"


def test_sync_batchnorm_matches_full_batch_stats():
    """SyncBN parity (reference batchnorm_utils.py:488): per-shard BN with
    the stats all-reduced over the device axis must equal plain BN over the
    concatenated batch."""
    from fedml_tpu.models.resnet import SYNC_BN_AXIS, CifarResNet

    x = jax.random.normal(jax.random.PRNGKey(0), (2, 8, 32, 32, 3))
    sync = CifarResNet(depth=20, num_classes=10, norm_kind="sync_batch")
    plain = CifarResNet(depth=20, num_classes=10, norm_kind="batch")
    variables = plain.init(jax.random.PRNGKey(1), x[0], train=False)

    def shard_apply(xs):
        return sync.apply(variables, xs, train=True,
                          mutable=["batch_stats"])

    out_sync, stats_sync = jax.vmap(
        shard_apply, axis_name=SYNC_BN_AXIS)(x)
    out_full, stats_full = plain.apply(
        variables, x.reshape((16, 32, 32, 3)), train=True,
        mutable=["batch_stats"])
    np.testing.assert_allclose(
        np.asarray(out_sync).reshape(16, 10), np.asarray(out_full),
        rtol=2e-3, atol=2e-4)
    # synced running stats are identical on every shard and equal full-batch
    for s_sync, s_full in zip(jax.tree.leaves(stats_sync),
                              jax.tree.leaves(stats_full)):
        np.testing.assert_allclose(np.asarray(s_sync[0]),
                                   np.asarray(s_sync[1]), rtol=1e-5)
        np.testing.assert_allclose(np.asarray(s_sync[0]),
                                   np.asarray(s_full), rtol=2e-3, atol=2e-4)
