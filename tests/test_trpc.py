"""TRPC tensor-socket backend: framing, transport, manager protocol, bench."""

import socket
import threading
import time

import numpy as np
import pytest

from fedml_tpu.comm.message import Message
from fedml_tpu.comm.trpc_backend import (
    TRPCCommManager,
    encode_frames,
    measure_roundtrip,
    read_frame,
)


def _pair(base_port):
    m0 = TRPCCommManager(rank=0, size=2, base_port=base_port)
    m1 = TRPCCommManager(rank=1, size=2, base_port=base_port)
    return m0, m1


def test_frame_roundtrip_over_socketpair():
    a, b = socket.socketpair()
    params = {
        "msg_type": 3,
        "sender": 1,
        "receiver": 0,
        "model_params": {
            "dense": {"kernel": np.arange(6, dtype=np.float32).reshape(2, 3)},
            "bias": np.zeros(3, np.float64),
        },
        "nested_list": [np.ones(2, np.int32), "tag", 7],
    }
    a.sendmsg(encode_frames(params))
    got = read_frame(b)
    a.close(), b.close()
    assert got["msg_type"] == 3 and got["nested_list"][1] == "tag"
    np.testing.assert_array_equal(
        got["model_params"]["dense"]["kernel"],
        params["model_params"]["dense"]["kernel"],
    )
    assert got["model_params"]["bias"].dtype == np.float64
    # arrays arrive writable (recv_into owns the buffer — no frombuffer view)
    got["model_params"]["bias"][0] = 1.0


def test_frame_bf16_tensor():
    import jax.numpy as jnp
    import ml_dtypes

    a, b = socket.socketpair()
    w = np.asarray(jnp.full((4,), 2.5, jnp.bfloat16))
    a.sendmsg(encode_frames({"w": w}))
    got = read_frame(b)
    a.close(), b.close()
    assert got["w"].dtype == np.dtype(ml_dtypes.bfloat16)
    np.testing.assert_allclose(got["w"].astype(np.float32), 2.5)


def test_trpc_send_receive_before_observer():
    m0, m1 = _pair(19890)
    received = []

    class _Obs:
        def receive_message(self, t, m):
            received.append((t, m.get("x")))

    t = None
    try:
        msg = Message(7, 0, 1)
        msg.add_params("x", np.full((4096,), 3.0, np.float32))
        m0.send_message(msg)  # inbox buffers until the loop starts
        m1.add_observer(_Obs())
        t = threading.Thread(target=m1.handle_receive_message, daemon=True)
        t.start()
        deadline = time.time() + 10
        while not received and time.time() < deadline:
            time.sleep(0.01)
        assert received and received[0][0] == 7
        np.testing.assert_array_equal(
            received[0][1], np.full((4096,), 3.0, np.float32))
        received[0][1][0] = 0.0  # writable
    finally:
        m0.stop_receive_message()
        m1.stop_receive_message()
        if t:
            t.join(timeout=5)


def test_trpc_manager_protocol_round():
    """The loopback round FSM from test_comm, over real TRPC sockets."""
    from tests.test_comm import _EchoClient, _EchoServer

    class _TrpcServer(_EchoServer):
        def __init__(self, args, size):
            # bypass _EchoServer.__init__ loopback wiring
            from fedml_tpu.comm.managers import ServerManager

            ServerManager.__init__(self, args, rank=0, size=size,
                                   backend="TRPC", base_port=19990)
            self.received = {}

    class _TrpcClient(_EchoClient):
        def __init__(self, args, rank, size):
            from fedml_tpu.comm.managers import ClientManager

            ClientManager.__init__(self, args, rank=rank, size=size,
                                   backend="TRPC", base_port=19990)

    size = 3
    server = _TrpcServer(None, size)
    clients = [_TrpcClient(None, r, size) for r in range(1, size)]
    threads = [threading.Thread(target=c.run, daemon=True) for c in clients]
    for th in threads:
        th.start()
    time.sleep(0.1)
    server.start_round()
    server.run()
    for th in threads:
        th.join(timeout=10)
    assert set(server.received) == {1, 2}
    np.testing.assert_array_equal(server.received[2]["w"], 2 * np.ones(3))


def test_trpc_large_payload_and_many_leaves():
    """Review regressions: (a) payloads larger than the socket send buffer
    must survive partial sendmsg writes; (b) pytrees with more leaves than
    IOV_MAX must be batched across syscalls."""
    m0, m1 = _pair(20290)
    try:
        big = np.random.default_rng(0).standard_normal(
            (16, 1024, 1024)).astype(np.float32)  # 64 MB
        many = {f"leaf{i}": np.full((3,), i, np.float32) for i in range(1500)}
        msg = Message(5, 0, 1)
        msg.add_params("big", big)
        msg.add_params("many", many)
        m0.send_message(msg)
        got = m1._inbox.get(timeout=60)
        np.testing.assert_array_equal(got.get("big"), big)
        assert len(got.get("many")) == 1500
        np.testing.assert_array_equal(
            got.get("many")["leaf1499"], np.full((3,), 1499, np.float32))
    finally:
        m0.stop_receive_message()
        m1.stop_receive_message()


def test_frame_tensor_placeholder_no_collision():
    """A user dict that *looks like* the old placeholder must round-trip as
    data (ExtType placeholders cannot collide)."""
    a, b = socket.socketpair()
    params = {"config": {"__t__": 0}, "w": np.ones(2, np.float32)}
    a.sendmsg(encode_frames(params))
    got = read_frame(b)
    a.close(), b.close()
    assert got["config"] == {"__t__": 0}
    np.testing.assert_array_equal(got["w"], np.ones(2, np.float32))


def test_frame_zero_length_tensor():
    """Advisor regression: a zero-size ndarray param used to make
    sendmsg_all busy-spin forever (sendmsg([b'']) returns 0)."""
    a, b = socket.socketpair()
    params = {"empty": np.zeros((0, 4), np.float32), "w": np.ones(2, np.float32)}
    a.sendmsg(encode_frames(params))  # would hang pre-fix via sendmsg_all path
    from fedml_tpu.comm.trpc_backend import sendmsg_all

    c, d = socket.socketpair()
    sendmsg_all(c, encode_frames(params))
    got = read_frame(b)
    got2 = read_frame(d)
    a.close(), b.close(), c.close(), d.close()
    for g in (got, got2):
        assert g["empty"].shape == (0, 4)
        np.testing.assert_array_equal(g["w"], np.ones(2, np.float32))


def test_frame_corrupt_header_raises():
    """Advisor regression: nbytes/shape mismatch and oversized claims must
    raise ValueError (not a strippable assert, not an unbounded alloc)."""
    import msgpack

    from fedml_tpu.comm.trpc_backend import _HDR, _MAGIC

    def send_raw(header_obj):
        a, b = socket.socketpair()
        header = msgpack.packb(header_obj, strict_types=False)
        a.sendall(_MAGIC + _HDR.pack(len(header)) + header)
        a.close()
        try:
            return read_frame(b)
        finally:
            b.close()

    with pytest.raises(ValueError, match="spec mismatch"):
        send_raw({"meta": None, "specs": [["float32", [2, 3], 999]]})
    with pytest.raises(ValueError, match="exceeds cap"):
        send_raw({"meta": None,
                  "specs": [["float32", [1 << 20, 1 << 20], 1 << 42]]})
    with pytest.raises(ValueError, match="negative"):
        send_raw({"meta": None,
                  "specs": [["float32", [-(1 << 40)], -4398046511104]]})
    # huge dims wrap in int64 np.prod -> caught as spec mismatch, never
    # an uncaught OverflowError and never a huge np.empty
    with pytest.raises(ValueError):
        send_raw({"meta": None, "specs": [["float32", [1 << 63], 4]]})
    with pytest.raises(ValueError):
        send_raw({"meta": None, "specs": [["float32", [(1 << 64) - 1], 4]]})
    with pytest.raises(ValueError, match="malformed frame header"):
        send_raw({"meta": None, "specs": [["nosuchdtype", [2], 8]]})
    with pytest.raises(ValueError, match="malformed frame header"):
        send_raw({"meta": None})


def test_trpc_latency_harness():
    m0, m1 = _pair(20090)
    try:
        res = measure_roundtrip(m0, m1, sizes=(1_000, 100_000), repeats=3)
        assert set(res) == {1_000, 100_000}
        assert all(v > 0 for v in res.values())
    finally:
        m0.stop_receive_message()
        m1.stop_receive_message()


def test_factory_builds_trpc():
    from fedml_tpu.comm.managers import create_comm_backend

    mgr = create_comm_backend("TRPC", rank=0, size=1, base_port=20190)
    try:
        assert isinstance(mgr, TRPCCommManager)
    finally:
        mgr.stop_receive_message()
