"""Agent daemon e2e: cli build -> server runner fan-out -> edge daemon
fetch/rewrite/fork -> status FSM reaches FINISHED.

Reference lifecycle: client_runner.py:129 (package), :147 (config rewrite),
:426 (fork), :619 (status FSM); server_runner.py:426 (fan-out).
"""

import json
import os
import subprocess
import sys
import textwrap
import zipfile

import yaml

from fedml_tpu.cli.runner import FedMLEdgeRunner, FedMLServerRunner
from fedml_tpu.comm.pubsub import FileSystemBroker
from fedml_tpu.comm.store import FileSystemBlobStore

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

ENTRY = textwrap.dedent(
    """
    import argparse, json, os
    import fedml_tpu
    from fedml_tpu.arguments import load_arguments

    p = argparse.ArgumentParser()
    p.add_argument("--cf", required=True)
    opts = p.parse_args()
    args = load_arguments(args_list=["--cf", opts.cf])
    fedml_tpu.init(args=args)
    history = fedml_tpu.run_simulation(args=args)
    with open("result.json", "w") as f:
        json.dump({"rounds": len(history), "rank": int(getattr(args, "rank", -1))}, f)
    """
)

CONFIG = {
    "common_args": {"random_seed": 0, "run_id": "agent_e2e"},
    "data_args": {"dataset": "mnist", "debug_small_data": True},
    "model_args": {"model": "lr"},
    "train_args": {
        "federated_optimizer": "FedAvg", "client_num_in_total": 4,
        "client_num_per_round": 4, "comm_round": 2, "epochs": 1,
        "batch_size": 8, "learning_rate": 0.1,
    },
    "validation_args": {"frequency_of_the_test": 1},
}


def _build_package(tmp_path) -> str:
    src = tmp_path / "src"
    cfg = tmp_path / "cfg"
    dist = tmp_path / "dist"
    src.mkdir(); cfg.mkdir()
    (src / "main.py").write_text(ENTRY)
    (cfg / "fedml_config.yaml").write_text(yaml.safe_dump(CONFIG))
    r = subprocess.run(
        [sys.executable, "-m", "fedml_tpu.cli", "build", "-t", "client",
         "-sf", str(src), "-ep", "main.py", "-cf", str(cfg), "-df", str(dist)],
        capture_output=True, text=True, cwd=REPO_ROOT,
        env=dict(os.environ, PYTHONPATH=REPO_ROOT),
    )
    assert r.returncode == 0, r.stderr
    pkg = dist / "fedml_tpu-client-package.zip"
    assert pkg.exists()
    with zipfile.ZipFile(pkg) as z:
        names = z.namelist()
    assert "package.json" in names and "source/main.py" in names
    return str(pkg)


def test_agent_daemon_end_to_end(tmp_path):
    pkg = _build_package(tmp_path)
    broker = FileSystemBroker(root=str(tmp_path / "broker"))
    store = FileSystemBlobStore(root=str(tmp_path / "blobs"))

    server = FedMLServerRunner(broker, store=store)
    edge = FedMLEdgeRunner(
        7, broker, store=store, home_dir=str(tmp_path / "edge_home")
    )
    edge.start()
    assert edge.status == "IDLE"

    # the child is a fresh interpreter: force the virtual CPU platform so it
    # never dials the TPU tunnel from inside a test
    child_env = {
        "PYTHONPATH": REPO_ROOT,
        "JAX_PLATFORMS": "cpu",
        "PALLAS_AXON_POOL_IPS": "",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
    }
    server.send_training_request_to_edges(
        run_id="r42", edge_ids=[7], package_path=pkg,
        dynamic_args={"comm_round": 2}, env=child_env,
    )
    assert edge.wait(timeout=240), "edge daemon never reached a terminal state"
    statuses = server.wait_for_edges([7], timeout=30)
    assert statuses[7] == "FINISHED", statuses

    # the forked run really executed inside the unzipped package dir
    run_dir = tmp_path / "edge_home" / "fedml_run" / "run_r42" / "edge_7" / "package"
    result = json.loads((run_dir / "result.json").read_text())
    assert result["rounds"] == 2
    assert result["rank"] == 7  # dynamic_args rewrote the packaged config
    # status file for the CLI
    status = json.loads((tmp_path / "edge_home" / "status.json").read_text())
    assert status["status"] == "FINISHED"
    edge.stop()
    broker.close()


def test_edge_daemon_reports_failure(tmp_path):
    broker = FileSystemBroker(root=str(tmp_path / "broker"))
    edge = FedMLEdgeRunner(3, broker, home_dir=str(tmp_path / "home"))
    edge.start()
    server = FedMLServerRunner(broker)
    server.send_training_request_to_edges(
        run_id="bad", edge_ids=[3], package_path=str(tmp_path / "missing.zip"),
    )
    assert edge.wait(timeout=30)
    assert server.wait_for_edges([3], timeout=10)[3] == "FAILED"
    edge.stop()
    broker.close()

def test_edge_daemon_restart_does_not_replay_finished_jobs(tmp_path):
    """A restarted daemon re-reads job-topic history (subscribe_from_start)
    but must skip runs its persisted history already records as terminal."""
    broker = FileSystemBroker(root=str(tmp_path / "broker"))
    home = str(tmp_path / "home")
    edge = FedMLEdgeRunner(5, broker, home_dir=home)
    edge.start()
    server = FedMLServerRunner(broker)
    # a job that fails fast (missing package) still reaches a terminal state
    server.send_training_request_to_edges(
        run_id="done1", edge_ids=[5], package_path=str(tmp_path / "nope.zip"))
    assert edge.wait(timeout=30)
    edge.stop()

    # restart: same home dir, fresh broker instance over the same dir
    broker2 = FileSystemBroker(root=str(tmp_path / "broker"))
    edge2 = FedMLEdgeRunner(5, broker2, home_dir=home)
    calls = []
    orig = edge2.retrieve_and_unzip_package
    edge2.retrieve_and_unzip_package = lambda *a, **k: (calls.append(a), orig(*a, **k))[1]
    edge2.start()
    import time as _time
    _time.sleep(0.5)  # let the poller replay topic history
    assert calls == [], "restarted daemon re-executed an already-terminal job"
    assert edge2._job_history == {"done1": "FAILED"}
    edge2.stop()
    broker.close()
    broker2.close()


def test_filesystem_broker_concurrent_publishers_no_loss(tmp_path):
    """Racing publishers (two broker instances over one dir, many threads)
    must never overwrite each other's sequence slots."""
    import threading as _threading

    b1 = FileSystemBroker(root=str(tmp_path / "broker"))
    b2 = FileSystemBroker(root=str(tmp_path / "broker"))
    got = []
    lock = _threading.Lock()
    b1.subscribe_from_start("t", lambda _t, p: (lock.acquire(), got.append(p), lock.release()))

    def blast(b, tag):
        for i in range(25):
            b.publish("t", f"{tag}:{i}".encode())

    threads = [_threading.Thread(target=blast, args=(b, tag))
               for b, tag in ((b1, "a"), (b2, "b"), (b1, "c"), (b2, "d"))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    deadline = __import__("time").time() + 10
    while len(got) < 100 and __import__("time").time() < deadline:
        __import__("time").sleep(0.05)
    assert len(got) == 100, f"lost {100 - len(got)} messages to publisher races"
    assert len(set(got)) == 100
    b1.close()
    b2.close()
