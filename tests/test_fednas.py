"""FedNAS bilevel search: alpha steps on a val split, genotype retrain.

VERDICT r2 missing #2: the reference alternates weight steps with
architecture-alpha steps through an Architect (architect.py:541,
train_search.py:435) and retrains the derived genotype. These tests run the
bilevel search federated, check the alphas actually move (they are NOT
ordinary FedAvg params any more), and check search-then-retrain beats a
random-genotype control on the same budget.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from fedml_tpu.algorithms.fednas import (
    FedNASConfig,
    alpha_mask,
    get_fednas_algorithm,
    run_fednas_search,
)
from fedml_tpu.data.federated import ArrayPair, build_federated_data
from fedml_tpu.models.darts import (
    OP_NAMES,
    DARTSSearchNet,
    DerivedNet,
    derive_genotype,
    genotype_to_cells,
)
from fedml_tpu.simulation.fed_sim import FedSimulator, SimConfig

H = 16


def _shape_dataset(n, seed):
    """Binary shapes with EQUAL total energy: class 1 = 3x3 plus sign,
    class 0 = 3x3 diagonal. Global average pooling of the raw image cannot
    separate them — conv ops can, so search should prefer convs."""
    rng = np.random.default_rng(seed)
    x = rng.normal(0.0, 0.3, size=(n, H, H, 1)).astype(np.float32)
    y = rng.integers(0, 2, size=n).astype(np.int32)
    for i in range(n):
        r, c = rng.integers(2, H - 3, size=2)
        if y[i]:
            x[i, r, c - 1:c + 2, 0] += 2.0  # plus sign
            x[i, r - 1:r + 2, c, 0] += 2.0
            x[i, r, c, 0] -= 2.0
        else:
            for d in (-1, 0, 1):  # diagonal + anti-diagonal (same energy)
                x[i, r + d, c + d, 0] += 2.0
                x[i, r + d, c - d, 0] += 2.0
            x[i, r, c, 0] -= 2.0
    return x, y


def _fed(n_clients=4, per_client=64, seed=0):
    x, y = _shape_dataset(n_clients * per_client + 128, seed)
    idx_map = {c: list(range(c * per_client, (c + 1) * per_client))
               for c in range(n_clients)}
    test = ArrayPair(x[-128:], y[-128:])
    return build_federated_data(
        ArrayPair(x[:n_clients * per_client], y[:n_clients * per_client]),
        test, idx_map, 2), test


def _accuracy(model, variables, test):
    logits = model.apply(variables, jnp.asarray(test.x), train=False)
    return float((jnp.argmax(logits, -1) == jnp.asarray(test.y)).mean())


def _retrain(genotype_cells, fed, test, rounds=6, seed=0):
    from fedml_tpu.algorithms import LocalTrainConfig, get_algorithm

    model = DerivedNet(genotype=genotype_cells, num_classes=2, channels=8)
    variables = model.init(jax.random.PRNGKey(seed),
                           jnp.zeros((1, H, H, 1), jnp.float32), train=False)

    def apply_fn(v, x, train=False, rngs=None, mutable=False):
        return model.apply(v, x, train=train)

    alg = get_algorithm("FedAvg", apply_fn,
                        LocalTrainConfig(lr=0.05, epochs=1, momentum=0.9))
    sim = FedSimulator(fed, alg, variables,
                       SimConfig(comm_round=rounds, client_num_in_total=4,
                                 client_num_per_round=4, batch_size=16,
                                 frequency_of_the_test=1000, seed=seed))
    sim.run(apply_fn=None, log_fn=None)
    return model, sim.params


def test_bilevel_search_moves_alphas_and_learns():
    fed, test = _fed()
    model = DARTSSearchNet(num_classes=2, channels=8, n_cells=2)
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, H, H, 1), jnp.float32), train=False)

    def apply_fn(v, x, train=False, rngs=None, mutable=False):
        return model.apply(v, x, train=train)

    hist, final, genotype = run_fednas_search(
        fed, variables, apply_fn,
        SimConfig(comm_round=8, client_num_in_total=4, client_num_per_round=4,
                  batch_size=16, frequency_of_the_test=1000, seed=0),
        FedNASConfig(lr=0.05, arch_lr=3e-3, epochs=1),
    )
    # alphas moved away from their zero init (bilevel step is live)
    amask = alpha_mask(final)
    moved = [float(jnp.abs(a).max())
             for a, m in zip(jax.tree.leaves(final), jax.tree.leaves(amask))
             if m]
    assert len(moved) == 4  # 2 cells x 2 mixed ops
    assert max(moved) > 1e-3
    assert hist[-1]["train_loss"] < hist[0]["train_loss"]
    assert len(genotype) == 4 and all(g["op"] in OP_NAMES for g in genotype)


def test_search_then_retrain_beats_random_genotype():
    fed, test = _fed()
    model = DARTSSearchNet(num_classes=2, channels=8, n_cells=2)
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, H, H, 1), jnp.float32), train=False)

    def apply_fn(v, x, train=False, rngs=None, mutable=False):
        return model.apply(v, x, train=train)

    _, final, genotype = run_fednas_search(
        fed, variables, apply_fn,
        SimConfig(comm_round=8, client_num_in_total=4, client_num_per_round=4,
                  batch_size=16, frequency_of_the_test=1000, seed=0),
        FedNASConfig(lr=0.05, arch_lr=3e-3, epochs=1),
    )
    searched = genotype_to_cells(genotype, n_cells=2)

    # random-genotype control: first sample that differs from the searched one
    rng = np.random.default_rng(7)
    while True:
        random_cells = tuple(
            tuple(rng.choice(OP_NAMES) for _ in range(2)) for _ in range(2))
        if random_cells != searched:
            break

    m_s, v_s = _retrain(searched, fed, test)
    m_r, v_r = _retrain(random_cells, fed, test)
    acc_s, acc_r = _accuracy(m_s, v_s, test), _accuracy(m_r, v_r, test)
    assert acc_s > acc_r, (searched, random_cells, acc_s, acc_r)
