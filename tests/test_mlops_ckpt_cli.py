"""Observability sinks, orbax checkpoint/resume, CLI commands."""

import json
import os

import numpy as np
import pytest

import fedml_tpu
from fedml_tpu.core.mlops import (
    MetricsSink,
    MLOpsMetrics,
    MLOpsProfilerEvent,
    SysStats,
)
from fedml_tpu.simulation import build_simulator


def test_metrics_sink_and_reports(tmp_path):
    sink = MetricsSink(path=str(tmp_path / "metrics.jsonl"))
    m = MLOpsMetrics(sink)
    m.report_server_training_round_info({"round": 1, "acc": 0.5})
    m.report_aggregated_model_info({"round": 1, "url": "local"})
    m.report_client_training_status(3, MLOpsMetrics.STATUS_RUNNING)
    sink.close()
    lines = [json.loads(l) for l in (tmp_path / "metrics.jsonl").read_text().splitlines()]
    assert [r["kind"] for r in lines] == ["round_info", "model_info", "client_status"]


def test_profiler_event_spans():
    sink = MetricsSink()
    ev = MLOpsProfilerEvent(sink=sink)
    ev.log_event_started("server.agg")
    ev.log_event_ended("server.agg")
    kinds = [r["kind"] for r in sink.records]
    assert kinds == ["event_started", "event_ended"]
    assert sink.records[1]["duration"] >= 0


def test_sys_stats_fields():
    s = SysStats().to_dict()
    assert s["host_memory_total_gb"] > 0
    assert "cpu_utilization" in s


def test_checkpoint_resume_matches_uninterrupted(tmp_path):
    cfg = dict(
        dataset="mnist", model="lr", debug_small_data=True,
        client_num_in_total=8, client_num_per_round=4, comm_round=6,
        learning_rate=0.1, batch_size=8, frequency_of_the_test=100,
        random_seed=0,
    )
    # uninterrupted run
    args = fedml_tpu.init(config=dict(cfg))
    sim, apply_fn = build_simulator(args)
    full_hist = sim.run(apply_fn=None, log_fn=None)
    full_params = sim.params

    # interrupted: 3 rounds with checkpoints, then resume to 6
    ck = str(tmp_path / "ck")
    args1 = fedml_tpu.init(config=dict(cfg, comm_round=3, checkpoint_dir=ck,
                                       checkpoint_frequency=1))
    sim1, _ = build_simulator(args1)
    sim1.run(apply_fn=None, log_fn=None)
    args2 = fedml_tpu.init(config=dict(cfg, comm_round=6, checkpoint_dir=ck,
                                       checkpoint_frequency=1))
    sim2, _ = build_simulator(args2)
    hist2 = sim2.run(apply_fn=None, log_fn=None)
    assert hist2[0]["round"] == 3  # resumed, not restarted

    import jax

    for a, b in zip(jax.tree.leaves(full_params), jax.tree.leaves(sim2.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_cli_version_build_login(tmp_path, monkeypatch):
    from click.testing import CliRunner
    import fedml_tpu.cli.main as cli_main

    monkeypatch.setattr(cli_main, "STATE_DIR", str(tmp_path / "state"))
    runner = CliRunner()
    out = runner.invoke(cli_main.cli, ["version"])
    assert out.exit_code == 0 and "fedml_tpu version" in out.output

    # build a package
    src = tmp_path / "src"; src.mkdir(); (src / "main.py").write_text("print('hi')")
    cfgd = tmp_path / "cfg"; cfgd.mkdir(); (cfgd / "c.yaml").write_text("a: 1")
    out = runner.invoke(cli_main.cli, [
        "build", "-t", "client", "-sf", str(src), "-ep", "main.py",
        "-cf", str(cfgd), "-df", str(tmp_path / "dist"),
    ])
    assert out.exit_code == 0, out.output
    assert (tmp_path / "dist" / "fedml_tpu-client-package.zip").exists()

    out = runner.invoke(cli_main.cli, ["login", "acct-42"])
    assert out.exit_code == 0
    out = runner.invoke(cli_main.cli, ["status"])
    assert "IDLE" in out.output
    out = runner.invoke(cli_main.cli, ["logout"])
    assert out.exit_code == 0


def test_cli_run_from_yaml(tmp_path, monkeypatch):
    from click.testing import CliRunner
    import fedml_tpu.cli.main as cli_main

    monkeypatch.setattr(cli_main, "STATE_DIR", str(tmp_path / "state"))
    cfg = tmp_path / "fedml_config.yaml"
    cfg.write_text("""
common_args:
  training_type: simulation
  random_seed: 0
data_args:
  dataset: mnist
  debug_small_data: true
model_args:
  model: lr
train_args:
  federated_optimizer: FedAvg
  client_num_in_total: 4
  client_num_per_round: 4
  comm_round: 2
  learning_rate: 0.1
  batch_size: 8
validation_args:
  frequency_of_the_test: 1
""")
    runner = CliRunner()
    out = runner.invoke(cli_main.cli, ["run", "--cf", str(cfg), "--backend", "sp"])
    assert out.exit_code == 0, out.output
    status = json.loads((tmp_path / "state" / "status.json").read_text())
    assert status["status"] == "FINISHED"


def test_cli_build_default_skeleton(tmp_path):
    """--source_folder default packages the stock entries (reference
    cli/build-package skeletons)."""
    import zipfile

    from click.testing import CliRunner
    import fedml_tpu.cli.main as cli_main

    cfgd = tmp_path / "cfg"; cfgd.mkdir(); (cfgd / "c.yaml").write_text("a: 1")
    runner = CliRunner()
    out = runner.invoke(cli_main.cli, [
        "build", "-t", "server", "-sf", "default", "-ep", "ignored.py",
        "-cf", str(cfgd), "-df", str(tmp_path / "dist"),
    ])
    assert out.exit_code == 0, out.output
    pkg = tmp_path / "dist" / "fedml_tpu-server-package.zip"
    with zipfile.ZipFile(pkg) as z:
        names = z.namelist()
        assert "source/tpu_server.py" in names
        meta = json.loads(z.read("package.json"))
        assert meta["entry_point"] == "tpu_server.py"


def test_comm_benchmark_hooks_emit_greppable_lines(caplog):
    """Reference communication/utils.py parity: tick/tock + round markers
    produce stable greppable prefixes."""
    import logging

    from fedml_tpu.comm.utils import (
        log_communication_tick,
        log_communication_tock,
        log_round_end,
        log_round_start,
    )

    with caplog.at_level(logging.INFO):
        log_round_start(0, 3)
        log_communication_tick(1, 0)
        log_communication_tock(1, 0)
        log_round_end(0, 3)
    text = caplog.text
    assert "--Benchmark start round 3 on rank 0" in text
    assert "--Benchmark tick: 1 to 0" in text
    assert "--Benchmark tock: 1 to 0 latency_ms=" in text
    assert "--Benchmark end round 3 on rank 0" in text


def test_mlops_configs_resolution(tmp_path, monkeypatch):
    """Reference MLOpsConfigs parity with per-key precedence:
    explicit args > cached file > env > home defaults."""
    from fedml_tpu.core.mlops import MLOpsConfigs

    cfgf = tmp_path / "mlops.json"
    cfgf.write_text(json.dumps({
        "mqtt_config": {"broker_dir": "/tmp/b1"},
        "s3_config": {"store_dir": "/tmp/s1"},
    }))

    class A:
        mlops_config_path = str(cfgf)

    # cached file supplies both keys
    mqtt, s3 = MLOpsConfigs(A()).fetch_configs()
    assert mqtt["broker_dir"] == "/tmp/b1" and s3["store_dir"] == "/tmp/s1"

    # explicit args BEAT the file AND a stale env var
    monkeypatch.setenv("FEDML_TPU_MQTT_DIR", str(tmp_path / "stale"))

    class B(A):
        mqtt_broker_dir = str(tmp_path / "explicit")

    mqtt, _ = MLOpsConfigs(B()).fetch_configs()
    assert mqtt["broker_dir"] == str(tmp_path / "explicit")

    # env applies when neither args nor file give the key
    partial = tmp_path / "partial.json"
    partial.write_text(json.dumps({"s3_config": {"store_dir": "/tmp/s9"}}))

    class C:
        mlops_config_path = str(partial)

    mqtt, s3 = MLOpsConfigs(C()).fetch_configs()
    assert mqtt["broker_dir"] == str(tmp_path / "stale")
    assert s3["store_dir"] == "/tmp/s9"

    # defaults under the home dir
    monkeypatch.delenv("FEDML_TPU_MQTT_DIR")
    monkeypatch.setenv("FEDML_TPU_HOME", str(tmp_path / "home"))
    mqtt, s3 = MLOpsConfigs(None).fetch_configs()
    assert mqtt["broker_dir"].startswith(str(tmp_path / "home"))

    # corrupt cache names itself instead of silently falling through
    bad = tmp_path / "bad.json"
    bad.write_text("{truncated")

    class D:
        mlops_config_path = str(bad)

    with pytest.raises(ValueError, match="bad.json"):
        MLOpsConfigs(D()).fetch_configs()


def test_device_trace_capture(tmp_path):
    """device_trace captures a real XLA profiler trace (TensorBoard
    trace-viewer files on disk) bracketed by sink span events."""
    import glob

    import jax.numpy as jnp

    sink = MetricsSink()
    ev = MLOpsProfilerEvent(sink=sink)
    tdir = str(tmp_path / "prof")  # name must not collide with patterns
    with ev.device_trace(tdir):
        x = jnp.ones((64, 64))
        (x @ x).block_until_ready()
    files = glob.glob(tdir + "/**/*", recursive=True)
    # a real capture writes trace-viewer/xplane payload files
    assert any(f.endswith((".pb", ".json.gz", ".trace.json.gz"))
               or "plugins/profile" in f for f in files), files
    kinds = [r["kind"] for r in sink.records]
    assert kinds == ["event_started", "event_ended"]
    assert sink.records[0]["event"] == "device_trace"
