"""Pallas flash attention: numerics vs dense, causal masking, gradients."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fedml_tpu.ops.attention import multihead_attention
from fedml_tpu.ops.pallas import flash_attention, flash_shapes_ok


def _qkv(B=2, T=256, H=2, Dh=64, seed=0):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(rng.normal(size=(B, T, H, Dh)), jnp.float32)  # noqa: E731
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [False, True])
def test_flash_matches_dense(causal):
    q, k, v = _qkv()
    dense = multihead_attention(q, k, v, causal=causal, impl="dense")
    flash = flash_attention(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(flash), np.asarray(dense), atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_gradients_match_dense(causal):
    q, k, v = _qkv(T=128)

    def loss_flash(q, k, v):
        # non-uniform cotangent so dq/dk/dv all get exercised beyond sum()
        out = flash_attention(q, k, v, causal)
        return (out * jnp.cos(jnp.arange(out.size).reshape(out.shape) * 0.01)).sum()

    def loss_dense(q, k, v):
        out = multihead_attention(q, k, v, causal=causal, impl="dense")
        return (out * jnp.cos(jnp.arange(out.size).reshape(out.shape) * 0.01)).sum()

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


def test_flash_gradients_long_context_T1024():
    """VERDICT #8 done-criterion: grad-vs-dense allclose at T=1024 and the
    (T, T) buffer absent from the compiled flash backward."""
    q, k, v = _qkv(B=1, T=1024, H=1, Dh=64, seed=3)

    def loss_flash(q, k, v):
        return flash_attention(q, k, v, True).sum()

    def loss_dense(q, k, v):
        return multihead_attention(q, k, v, causal=True, impl="dense").sum()

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-4)

    # memory assertion: no (1024, 1024) intermediate anywhere in the flash
    # grad program; the dense grad program must contain one (sanity check
    # that the probe actually detects the buffer).
    flash_hlo = jax.jit(jax.grad(loss_flash, argnums=(0, 1, 2))).lower(
        q, k, v).as_text()
    dense_hlo = jax.jit(jax.grad(loss_dense, argnums=(0, 1, 2))).lower(
        q, k, v).as_text()
    assert "1024x1024" not in flash_hlo
    assert "1024x1024" in dense_hlo


def test_auto_dispatch_guard():
    assert flash_shapes_ok(256, 64)
    assert flash_shapes_ok(1024, 128)
    assert not flash_shapes_ok(100, 64)   # ragged T
    assert not flash_shapes_ok(256, 48)   # lane-hostile Dh


def test_vmem_gate_boundaries():
    """The full-K/V VMEM staging bound: measured-good shapes pass, the
    measured-failing one is rejected, and f32 halves the reachable T."""
    from fedml_tpu.ops.pallas import flash_shapes_ok, flash_vmem_ok

    assert flash_shapes_ok(12288, 64, itemsize=2)   # largest verified (bf16)
    assert not flash_shapes_ok(16384, 64, itemsize=2)  # measured VMEM fail
    assert not flash_shapes_ok(12288, 64, itemsize=4)  # f32 doubles staging
    assert flash_shapes_ok(6144, 64, itemsize=4)
    assert flash_vmem_ok(12288, 64) and not flash_vmem_ok(12289 * 2, 64)


def test_auto_dispatch_warns_on_vmem_fallback(caplog):
    import logging

    import jax.numpy as jnp

    from fedml_tpu.ops.attention import multihead_attention

    q = jnp.zeros((1, 16384, 1, 64), jnp.bfloat16)
    with caplog.at_level(logging.WARNING):
        multihead_attention(q[:, :128], q[:, :128], q[:, :128])  # small: no warn
        assert "VMEM ceiling" not in caplog.text
        multihead_attention(q, q, q)
    assert "VMEM ceiling" in caplog.text
