"""Pallas flash attention: numerics vs dense, causal masking, gradients."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fedml_tpu.ops.attention import multihead_attention
from fedml_tpu.ops.pallas import flash_attention, flash_shapes_ok


def _qkv(B=2, T=256, H=2, Dh=64, seed=0):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(rng.normal(size=(B, T, H, Dh)), jnp.float32)  # noqa: E731
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [False, True])
def test_flash_matches_dense(causal):
    q, k, v = _qkv()
    dense = multihead_attention(q, k, v, causal=causal, impl="dense")
    flash = flash_attention(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(flash), np.asarray(dense), atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_gradients_match_dense(causal):
    q, k, v = _qkv(T=128)

    def loss_flash(q, k, v):
        # non-uniform cotangent so dq/dk/dv all get exercised beyond sum()
        out = flash_attention(q, k, v, causal)
        return (out * jnp.cos(jnp.arange(out.size).reshape(out.shape) * 0.01)).sum()

    def loss_dense(q, k, v):
        out = multihead_attention(q, k, v, causal=causal, impl="dense")
        return (out * jnp.cos(jnp.arange(out.size).reshape(out.shape) * 0.01)).sum()

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


def test_flash_gradients_long_context_T1024():
    """VERDICT #8 done-criterion: grad-vs-dense allclose at T=1024 and the
    (T, T) buffer absent from the compiled flash backward."""
    q, k, v = _qkv(B=1, T=1024, H=1, Dh=64, seed=3)

    def loss_flash(q, k, v):
        return flash_attention(q, k, v, True).sum()

    def loss_dense(q, k, v):
        return multihead_attention(q, k, v, causal=True, impl="dense").sum()

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-4)

    # memory assertion: no (1024, 1024) intermediate anywhere in the flash
    # grad program; the dense grad program must contain one (sanity check
    # that the probe actually detects the buffer).
    flash_hlo = jax.jit(jax.grad(loss_flash, argnums=(0, 1, 2))).lower(
        q, k, v).as_text()
    dense_hlo = jax.jit(jax.grad(loss_dense, argnums=(0, 1, 2))).lower(
        q, k, v).as_text()
    assert "1024x1024" not in flash_hlo
    assert "1024x1024" in dense_hlo


def test_auto_impl_memory_aware():
    """Dispatch goes flash below the T=4096 speed crossover whenever one
    layer's saved dense probabilities would cross 512 MB (the MFU-bench
    lesson: 12 layers x 2.15 GB of probs at B=16 H=16 T=2048 = 26 GB)."""
    from fedml_tpu.ops.attention import auto_attention_impl

    assert auto_attention_impl(4, 8, 2048, 64) == "dense"    # 268 MB: speed
    assert auto_attention_impl(16, 16, 2048, 64) == "flash"  # 2.1 GB/layer
    assert auto_attention_impl(1, 1, 8192, 64) == "flash"    # past crossover
    # memory wants flash but shapes refuse (lane-hostile Dh) -> dense
    assert auto_attention_impl(16, 16, 2048, 48) == "dense"


def test_auto_dispatch_guard():
    assert flash_shapes_ok(256, 64)
    assert flash_shapes_ok(1024, 128)
    assert not flash_shapes_ok(100, 64)   # ragged T
    assert not flash_shapes_ok(256, 48)   # lane-hostile Dh


def test_shapes_gate_is_t_independent():
    """The K-blocked kernel's VMEM use is O(block * Dh), so the gate no
    longer depends on T (the round-2 full-K/V cap at T~12k is gone) —
    only block divisibility and lane-friendly Dh matter."""
    from fedml_tpu.ops.pallas import flash_shapes_ok, flash_vmem_ok

    assert flash_shapes_ok(12288, 64, itemsize=2)
    assert flash_shapes_ok(16384, 64, itemsize=2)   # round-2 measured fail
    assert flash_shapes_ok(65536, 64, itemsize=2)   # long context single chip
    assert flash_shapes_ok(16384, 64, itemsize=4)   # f32 no longer halves T
    assert not flash_shapes_ok(12288 + 100, 64)     # block divisibility
    assert not flash_shapes_ok(12288, 48)           # lane-unfriendly Dh
    assert flash_vmem_ok(65536, 64) and flash_vmem_ok(65536, 128)


def test_auto_block_is_lane_legal():
    """Blocks must be multiples of 128 (Mosaic lane dim) that divide T."""
    from fedml_tpu.ops.pallas.flash_attention import auto_block

    assert auto_block(8192) == 1024
    assert auto_block(1024) == 512    # measured: T<=1024 prefers T//2
    assert auto_block(12288) == 1024
    assert auto_block(640) == 128     # 320 divides but is lane-illegal
    assert auto_block(384) == 128
    assert auto_block(100) is None
    for T in (256, 384, 640, 896, 2048, 12288):
        b = auto_block(T)
        assert b % 128 == 0 and T % b == 0


def test_shapes_gate_rejects_oversized_explicit_blocks():
    """flash_shapes_ok must veto block sizes the VMEM budget can't hold
    (2048 blocks fail to compile on the v5e)."""
    from fedml_tpu.ops.pallas import flash_shapes_ok

    assert flash_shapes_ok(8192, 64, block_q=1024, block_k=1024)
    assert not flash_shapes_ok(8192, 64, block_q=2048, block_k=2048)


def test_auto_dispatch_warns_on_long_dense_fallback(caplog):
    """An untileable long T falls back to dense LOUDLY (O(T^2) HBM)."""
    import logging

    import jax.numpy as jnp

    from fedml_tpu.ops.attention import multihead_attention

    q = jnp.zeros((1, 8192 + 8, 1, 64), jnp.bfloat16)  # 8200: no 128-divisor
    with caplog.at_level(logging.WARNING):
        multihead_attention(q, q, q)
    assert "DENSE O(T^2)" in caplog.text


def test_auto_dispatch_uses_flash_at_long_t(caplog):
    """T=16384 — the round-2 dense-fallback length — now dispatches to the
    K-blocked flash kernel with no VMEM warning."""
    import logging

    import jax.numpy as jnp

    from fedml_tpu.ops.attention import multihead_attention

    q = jnp.zeros((1, 16384, 1, 64), jnp.bfloat16)
    with caplog.at_level(logging.WARNING):
        out = multihead_attention(q, q, q)
    assert out.shape == q.shape
    assert "DENSE" not in caplog.text  # no dense fallback = flash engaged
