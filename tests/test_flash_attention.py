"""Pallas flash attention: numerics vs dense, causal masking, gradients."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fedml_tpu.ops.attention import multihead_attention
from fedml_tpu.ops.pallas import flash_attention, flash_shapes_ok


def _qkv(B=2, T=256, H=2, Dh=64, seed=0):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(rng.normal(size=(B, T, H, Dh)), jnp.float32)  # noqa: E731
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [False, True])
def test_flash_matches_dense(causal):
    q, k, v = _qkv()
    dense = multihead_attention(q, k, v, causal=causal, impl="dense")
    flash = flash_attention(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(flash), np.asarray(dense), atol=2e-5)


def test_flash_gradients_match_dense():
    q, k, v = _qkv(T=128)

    def loss_flash(q, k, v):
        return flash_attention(q, k, v, True).sum()

    def loss_dense(q, k, v):
        return multihead_attention(q, k, v, causal=True, impl="dense").sum()

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


def test_auto_dispatch_guard():
    assert flash_shapes_ok(256, 64)
    assert flash_shapes_ok(1024, 128)
    assert not flash_shapes_ok(100, 64)   # ragged T
    assert not flash_shapes_ok(256, 48)   # lane-hostile Dh
