"""Mobile (Beehive) model builders: deployable artifact roundtrip + the
server-side evaluation path (reference model/mobile/mnn_lenet.py:35,
mnn_resnet.py:137, cross_device/server_mnn/fedml_aggregator.py:171)."""

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.models import (
    MobileLeNet5,
    build_mobile_model_file,
    load_mobile_model_file,
)


def test_mobile_artifact_roundtrip(tmp_path):
    path = str(tmp_path / "lenet5.fedml")
    art = build_mobile_model_file("lenet5", path, seed=3)
    assert (tmp_path / "lenet5.fedml").read_bytes() == art

    model, variables = load_mobile_model_file(path)
    ref = MobileLeNet5(num_classes=10).init(
        jax.random.PRNGKey(3), jnp.zeros((1, 28, 28, 1))
    )
    for a, b in zip(jax.tree.leaves(variables), jax.tree.leaves(ref)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    x = jnp.asarray(np.random.default_rng(0).normal(size=(4, 28, 28, 1)),
                    jnp.float32)
    logits = model.apply(variables, x)
    assert logits.shape == (4, 10)


def test_mobile_lenet_learns():
    import optax

    model = MobileLeNet5(num_classes=2)
    rng = np.random.default_rng(0)
    x = rng.normal(size=(64, 28, 28, 1)).astype(np.float32)
    y = (x.mean(axis=(1, 2, 3)) > 0).astype(np.int32)
    variables = model.init(jax.random.PRNGKey(0), jnp.asarray(x[:1]))
    opt = optax.adam(1e-3)
    opt_state = opt.init(variables)

    @jax.jit
    def step(p, s, x, y):
        def loss_fn(p):
            logits = model.apply(p, x)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, y).mean()
        l, g = jax.value_and_grad(loss_fn)(p)
        updates, s = opt.update(g, s, p)
        return optax.apply_updates(p, updates), s, l

    losses = []
    for _ in range(30):
        variables, opt_state, l = step(
            variables, opt_state, jnp.asarray(x), jnp.asarray(y))
        losses.append(float(l))
    assert losses[-1] < losses[0] * 0.5, losses
