"""Cross-silo (Octopus) horizontal FL over loopback + gRPC backends."""

import threading

import numpy as np
import pytest

import fedml_tpu
from fedml_tpu.comm import LoopbackHub
from fedml_tpu.cross_silo import FedML_Horizontal


def _args(**kw):
    base = dict(
        dataset="mnist", model="lr", debug_small_data=True,
        client_num_in_total=4, client_num_per_round=2, comm_round=3,
        learning_rate=0.1, epochs=1, batch_size=8, frequency_of_the_test=1,
        random_seed=0,
    )
    base.update(kw)
    return fedml_tpu.init(config=base)


def _run_deployment(args, n_clients, backend="LOOPBACK", **kw):
    hub = LoopbackHub() if backend == "LOOPBACK" else None
    extra = dict(hub=hub) if hub else kw
    server = FedML_Horizontal(args, 0, n_clients, backend=backend, **extra)
    clients = [
        FedML_Horizontal(args, rank, n_clients, backend=backend, **extra)
        for rank in range(1, n_clients + 1)
    ]
    threads = [threading.Thread(target=c.run, daemon=True) for c in clients]
    for t in threads:
        t.start()
    server.start()
    server.run()
    for t in threads:
        t.join(timeout=60)
    return server


def test_cross_silo_loopback_full_run():
    args = _args()
    server = _run_deployment(args, n_clients=2)
    assert len(server.history) == 3
    accs = [h["test_acc"] for h in server.history]
    assert accs[-1] > 0.4, accs


def test_cross_silo_online_handshake_gates_init():
    """INIT must not be sent until every selected client reports IDLE."""
    args = _args(comm_round=1)
    hub = LoopbackHub()
    server = FedML_Horizontal(args, 0, 2, backend="LOOPBACK", hub=hub)
    server.register_message_receive_handlers()
    server.start()  # sends CHECK_CLIENT_STATUS to both clients
    assert not server.is_initialized
    from fedml_tpu.cross_silo import MyMessage
    from fedml_tpu.comm import Message

    online = Message(MyMessage.MSG_TYPE_C2S_CLIENT_STATUS, 1, 0)
    online.add_params(MyMessage.MSG_ARG_KEY_CLIENT_STATUS, MyMessage.MSG_CLIENT_STATUS_IDLE)
    server.receive_message(online.get_type(), online)
    assert not server.is_initialized  # one of two online
    online2 = Message(MyMessage.MSG_TYPE_C2S_CLIENT_STATUS, 2, 0)
    online2.add_params(MyMessage.MSG_ARG_KEY_CLIENT_STATUS, MyMessage.MSG_CLIENT_STATUS_IDLE)
    server.receive_message(online2.get_type(), online2)
    assert server.is_initialized


def test_cross_silo_subset_cohort_no_deadlock():
    """client_num_per_round < connected silos: the round barrier must track
    the cohort, not the full silo set (review finding: full-flag-dict check
    deadlocks)."""
    args = _args(client_num_in_total=3, client_num_per_round=2, comm_round=3)
    server = _run_deployment(args, n_clients=3)
    assert len(server.history) == 3


def test_cross_silo_grpc_full_run():
    pytest.importorskip("grpc")
    args = _args(comm_round=2, grpc_base_port=19200)
    server = _run_deployment(args, n_clients=2, backend="GRPC", base_port=19200)
    assert len(server.history) == 2
    assert np.isfinite(server.history[-1]["test_acc"])


def test_cross_silo_mqtt_s3_real_wire_full_run(tmp_path):
    """Full cross-silo FL deployment over the PRODUCTION transport pair:
    control plane on real MQTT 3.1.1 TCP connections (one client per rank,
    like the reference's paho sessions), weights through the blob store.
    The broker endpoint comes from the reference's mqtt-config keys
    (BROKER_HOST/BROKER_PORT) via MLOpsConfigs, exactly as a hosted
    deployment would resolve it."""
    import json

    from fedml_tpu.comm.mqtt_wire import MqttBroker

    broker = MqttBroker()
    cfg_path = tmp_path / "mlops_config.json"
    cfg_path.write_text(json.dumps({
        "mqtt_config": {"BROKER_HOST": broker.host,
                        "BROKER_PORT": broker.port},
        "s3_config": {"store_dir": str(tmp_path / "store")},
    }))
    try:
        args = _args(comm_round=2, run_id="wire_silo",
                     mlops_config_path=str(cfg_path))
        server = _run_deployment(args, n_clients=2, backend="MQTT_S3")
        assert len(server.history) == 2
        assert np.isfinite(server.history[-1]["test_acc"])
        # every rank held its own live MQTT session on the broker
    finally:
        broker.close()


def test_cross_silo_per_client_local_eval():
    """local_test_on_all_clients=True: eval rounds report the reference
    MPI aggregator's weighted per-client local train/test stats
    (FedAVGAggregator.py:128-180 semantics) alongside the global acc."""
    args = _args(local_test_on_all_clients=True)
    server = _run_deployment(args, n_clients=2)
    assert len(server.history) == 3
    for rec in server.history:
        for key in ("local_train_acc", "local_train_loss",
                    "local_test_acc", "local_test_loss", "test_acc"):
            assert key in rec, (key, rec)
        assert 0.0 <= rec["local_train_acc"] <= 1.0
    # training on MNIST LR: local-train accuracy ends well above chance
    assert server.history[-1]["local_train_acc"] > 0.5
