"""Compiled multi-round dispatch (``rounds_per_dispatch``): bit-exact
parity with the per-round engine, block planning at hook boundaries,
checkpoint resume from mid-block indices, typed incompatibility errors,
amortized phase accounting, and the one-compile-per-(R, shapes) guard.
"""

from __future__ import annotations

import math
import tempfile

import numpy as np
import pytest

import jax

import fedml_tpu
from fedml_tpu.core import telemetry
from fedml_tpu.simulation import build_simulator
from fedml_tpu.simulation.fed_sim import ScanIncompatibleError

# timing keys vary run to run; everything else must match bit for bit
TIMING_KEYS = {"round_time", "dispatch_time", "pack_time", "pack_wait",
               "overlap", "phases", "scan_rounds"}


def _args(**kw):
    base = dict(
        dataset="cifar10", model="lr", partition_method="hetero",
        partition_alpha=0.3, debug_small_data=True,
        client_num_in_total=12, client_num_per_round=6, comm_round=7,
        learning_rate=0.05, epochs=1, batch_size=16,
        frequency_of_the_test=100, random_seed=0,
    )
    base.update(kw)
    return fedml_tpu.init(config=base)


def _flat(params):
    return np.concatenate(
        [np.asarray(l, np.float64).ravel() for l in jax.tree.leaves(params)])


def _run(**kw):
    sim, apply_fn = build_simulator(_args(**kw))
    hist = sim.run(apply_fn, log_fn=None)
    stripped = [{k: v for k, v in r.items() if k not in TIMING_KEYS}
                for r in hist]
    return sim, hist, stripped


# ------------------------------------------------------------ bit-exactness

@pytest.mark.parametrize("kw", [
    pytest.param(dict(sanitize_updates=True), id="fedavg_sanitize"),
    pytest.param(dict(federated_optimizer="SCAFFOLD"), id="scaffold_arena"),
    pytest.param(dict(comm_codec="delta|topk:0.01|q8"), id="codec_ef_carry"),
    pytest.param(dict(client_dropout_rate=0.3), id="dropout"),
])
def test_scanned_history_bit_exact_vs_per_round(kw):
    # eval fires at round 0 and the last round, so the 7-round plan holds
    # a length-1 block, a full block, and a truncated tail block — SCAFFOLD
    # arena rows and codec EF residuals must carry across all three
    s1, _, h1 = _run(**kw)
    s4, _, h4 = _run(rounds_per_dispatch=4, **kw)
    assert np.array_equal(_flat(s1.params), _flat(s4.params))
    assert h1 == h4


def test_scan_blocks_split_at_eval_rounds():
    kw = dict(sanitize_updates=True, frequency_of_the_test=2)
    s1, _, h1 = _run(**kw)
    s4, raw4, h4 = _run(rounds_per_dispatch=4, **kw)
    assert np.array_equal(_flat(s1.params), _flat(s4.params))
    assert h1 == h4
    # eval rounds (0, 2, 4, 6) each end their block: the plan is
    # [0], [1,2], [3,4], [5,6] — never a scanned block spanning an eval
    by_round = {r["round"]: r for r in raw4}
    assert "scan_rounds" not in by_round[0]          # length-1 → per-round
    for r in (1, 2, 3, 4, 5, 6):
        assert by_round[r]["scan_rounds"] == 2


def test_scan_blocks_split_at_checkpoint_rounds(tmp_path):
    def kw(sub):
        d = tmp_path / sub
        d.mkdir()
        return dict(federated_optimizer="SCAFFOLD", checkpoint_dir=str(d),
                    checkpoint_frequency=3, frequency_of_the_test=1000,
                    resume=False)

    s1, _, h1 = _run(**kw("per_round"))
    s4, raw4, h4 = _run(rounds_per_dispatch=4, **kw("scan"))
    assert np.array_equal(_flat(s1.params), _flat(s4.params))
    assert h1 == h4
    # round 0 always evals, checkpoints land after rounds 2 and 5 → the
    # plan is [0], [1,2], [3,4,5], [6]
    by_round = {r["round"]: r for r in raw4}
    assert "scan_rounds" not in by_round[0]
    assert "scan_rounds" not in by_round[6]
    for r in (1, 2):
        assert by_round[r]["scan_rounds"] == 2
    for r in (3, 4, 5):
        assert by_round[r]["scan_rounds"] == 3


def test_checkpoint_resume_mid_plan_matches_per_round():
    outs = {}
    for tag, rpd in (("per_round", 1), ("scan", 4)):
        with tempfile.TemporaryDirectory() as d:
            kw = dict(federated_optimizer="SCAFFOLD", checkpoint_dir=d,
                      checkpoint_frequency=3, rounds_per_dispatch=rpd)
            _run(comm_round=3, **kw)  # writes the round-2 checkpoint
            # resume restarts at round 3 — NOT a multiple of R=4, so the
            # scan plan must re-anchor mid-block
            s, _, h = _run(comm_round=7, resume=True, **kw)
            outs[tag] = (_flat(s.params), h)
    assert np.array_equal(outs["per_round"][0], outs["scan"][0])
    assert outs["per_round"][1] == outs["scan"][1]


def test_arena_capacity_overflow_falls_back_per_round():
    # a 4-round slot union larger than the arena forces the block onto the
    # per-round path — still bit-exact, never a wrong-slot scatter
    kw = dict(federated_optimizer="SCAFFOLD", client_state_capacity=7)
    s1, _, h1 = _run(**kw)
    s4, raw4, h4 = _run(rounds_per_dispatch=4, **kw)
    assert np.array_equal(_flat(s1.params), _flat(s4.params))
    assert h1 == h4
    assert all("scan_rounds" not in r for r in raw4)


def test_block_packer_matches_per_round_packing():
    # the vectorized block packer must reproduce the per-round packer's
    # rectangles exactly: same shuffles, same dropout, same index rows
    sim, _ = build_simulator(_args(client_dropout_rate=0.25,
                                   rounds_per_dispatch=3))
    rounds = (1, 2, 3)
    blk = sim.build_block_inputs(rounds)
    for k, r in enumerate(rounds):
        ri = sim.build_round_inputs(r)
        c_real = len(ri.client_ids)
        assert np.array_equal(blk.ids[k], np.asarray(ri.client_ids))
        assert np.array_equal(blk.xs["idx"][k, :c_real],
                              ri.payload["idx"].astype(np.int32))
        ns = np.asarray(ri.payload["num_samples"])
        assert np.array_equal(blk.xs["num_samples"][k, :c_real],
                              ns.astype(np.int32))
        # the in-scan mask rebuild: arange(bs) < num_samples row-wise
        nb, bs = ri.payload["mask"].shape[1:]
        rebuilt = (np.arange(nb * bs)[None, :]
                   < ns[:, None]).astype(np.float32).reshape(-1, nb, bs)
        assert np.array_equal(rebuilt, ri.payload["mask"])


# ------------------------------------------------------- typed incompatibility

@pytest.mark.parametrize("kw", [
    pytest.param(dict(watchdog_factor=3.0), id="watchdog"),
    pytest.param(dict(attack_type="sign_flip", byzantine_client_num=1),
                 id="attack_transform"),
    pytest.param(dict(federated_optimizer="SCAFFOLD",
                      client_state_capacity=8,
                      client_state_spill_dir="__tmp_spill__"),
                 id="disk_spill_arena"),
    pytest.param(dict(federated_optimizer="SCAFFOLD",
                      client_state_backend="dict"), id="dict_state_backend"),
    pytest.param(dict(cohort_schedule="packed"), id="packed_schedule"),
    pytest.param(dict(async_mode=True), id="async_engine"),
])
def test_incompatible_configs_rejected_typed(kw, tmp_path):
    if "client_state_spill_dir" in kw:
        kw = dict(kw, client_state_spill_dir=str(tmp_path))
    with pytest.raises(ScanIncompatibleError):
        build_simulator(_args(rounds_per_dispatch=4, **kw))


def test_scan_incompatible_error_is_a_value_error():
    # callers catching the PR-6 mesh-refusal pattern keep working
    assert issubclass(ScanIncompatibleError, ValueError)


def test_rounds_per_dispatch_below_one_rejected():
    with pytest.raises(ValueError):
        build_simulator(_args(rounds_per_dispatch=0))


def test_rounds_per_dispatch_typo_rejected_at_config_load():
    # a YAML typo fails at load_arguments naming the key, not as a
    # TypeError deep inside SimConfig construction
    with pytest.raises(ValueError, match="rounds_per_dispatch"):
        fedml_tpu.init(config=dict(rounds_per_dispatch="4x"))


def test_multi_tenant_round_gate_rejected_at_run():
    sim, apply_fn = build_simulator(_args(rounds_per_dispatch=4))
    sim._round_gate = lambda r: None  # what multi_run's scheduler installs
    with pytest.raises(ScanIncompatibleError):
        sim.run(apply_fn, log_fn=None)


def test_robust_defense_stays_scan_compatible():
    # a Krum-family robust aggregator is pure XLA inside the round body —
    # must NOT be refused, and must stay bit-exact under fusion
    kw = dict(federated_optimizer="FedAvg_Robust", defense_type="krum",
              byzantine_n=1)
    s1, _, h1 = _run(**kw)
    s4, raw4, h4 = _run(rounds_per_dispatch=4, **kw)
    assert any(r.get("scan_rounds") for r in raw4)
    assert np.array_equal(_flat(s1.params), _flat(s4.params))
    assert h1 == h4


# ------------------------------------------------------------- telemetry

def test_amortized_phases_sum_exactly_to_round_time():
    reg = telemetry.get_registry()
    blocks_before = reg.counter("fedml_scan_blocks_total").value
    _, raw4, _ = _run(rounds_per_dispatch=4, sanitize_updates=True)
    scanned = [r for r in raw4 if "scan_rounds" in r]
    assert scanned, "expected at least one fused block"
    for r in raw4:
        assert math.isclose(sum(r["phases"].values()), r["round_time"],
                            rel_tol=1e-6, abs_tol=1e-9)
    for r in scanned:
        assert {"pack_wait", "scan_pack", "dispatch",
                "device"} <= set(r["phases"])
    # plan for 7 rounds with eval at 0 and 6: [0], [1..4], [5,6] → 2 fused
    blocks = reg.counter("fedml_scan_blocks_total").value - blocks_before
    assert blocks == 2


def test_one_compilation_per_R_and_shapes():
    # the same (R, shapes) pair across MORE blocks must not compile again:
    # 13 rounds plan [0],[1-4],[5-8],[9-12] reuses the length-4 program
    # twice more than 7 rounds' [0],[1-4],[5,6] adds a length-2 tail
    def _compiles(comm_round):
        reg = telemetry.get_registry()
        snap = reg.snapshot()["counters"]
        before = sum(v for k, v in snap.items()
                     if k.startswith("fedml_jax_compilation_events_total"))
        _run(rounds_per_dispatch=4, comm_round=comm_round)
        snap = reg.snapshot()["counters"]
        return sum(v for k, v in snap.items()
                   if k.startswith("fedml_jax_compilation_events_total")) \
            - before

    base = _compiles(7)    # block lengths {1, 4, 2}
    again = _compiles(15)  # block lengths {1, 4, 4, 4, 2} — same programs
    assert again <= base


def test_default_rounds_per_dispatch_is_classic_path():
    sim, _ = build_simulator(_args())
    assert sim._scan_rounds == 1
    _, raw, _ = _run()
    assert all("scan_rounds" not in r for r in raw)
