"""Compressed update plane (comm/codec.py): spec grammar, per-stage numpy
oracles, stochastic-rounding determinism, numpy<->JAX bit parity, 4-backend
frame parity, and the end-to-end accuracy-vs-bytes acceptance drill.

The oracles pin the arithmetic contracts the codec advertises:

- q8 error < amax/32 and q4 error < amax/2 per 256-chunk (pow2 scales);
- delta as terminal stage is bit-exact for float32 (f64 carrier);
- top-k with error feedback converges on a quadratic where plain top-k
  stalls at its truncation bias;
- the same (seed, round, client) always yields the same bytes, and any
  change to the tuple changes the rounding stream.
"""

import logging
import math
import threading
import time
from types import SimpleNamespace

import numpy as np
import pytest

import fedml_tpu
from fedml_tpu.comm import (
    LoopbackCommManager,
    LoopbackHub,
    InMemoryBlobStore,
    InProcessBroker,
    Message,
    MqttS3CommManager,
)
from fedml_tpu.comm import codec as codec_mod
from fedml_tpu.comm.codec import (
    UpdateCodec,
    build_stacked_roundtrip,
    decode_tree,
    dequantize,
    downlink_spec,
    encode_tree,
    frame_nbytes,
    is_codec_frame,
    pack_int4,
    parse_codec_spec,
    resolve_codec_spec,
    resolve_downlink_spec,
    spec_wire_nbytes,
    stochastic_quantize,
    tree_nbytes,
    unpack_int4,
)
from fedml_tpu.comm.message import compress_tree, decompress_tree
from fedml_tpu.core import telemetry


# ------------------------------------------------------------ spec grammar

def test_parse_spec_full_pipeline():
    cs = parse_codec_spec("delta|topk:0.01|q8")
    assert cs.delta and cs.topk == 0.01 and cs.bits == 8 and cs.bound == 127
    assert parse_codec_spec("q4").bound == 7
    assert parse_codec_spec("delta").topk is None
    assert parse_codec_spec("topk:1.0|q4").topk == 1.0


@pytest.mark.parametrize("bad", [
    "", "zstd", "q8|q4", "topk:0", "topk:1.5", "topk:x", "topk:",
    "q8|delta", "topk:0.1|delta", "q8|topk:0.1", "delta|delta",
])
def test_parse_spec_rejects(bad):
    with pytest.raises(ValueError):
        parse_codec_spec(bad)


def test_resolve_spec_precedence():
    # explicit comm_codec beats the deprecated comm_quantize shim
    assert resolve_codec_spec(
        SimpleNamespace(comm_codec="q4", comm_quantize=True)) == "q4"
    # "none"/"off" disable even with the shim set
    assert resolve_codec_spec(
        SimpleNamespace(comm_codec="none", comm_quantize=True)) is None
    # unset -> codec off entirely
    assert resolve_codec_spec(SimpleNamespace()) is None
    # "auto" resolves per wire backend
    auto = SimpleNamespace(comm_codec="auto")
    assert resolve_codec_spec(auto, "MQTT_S3") == "delta|topk:0.01|q8"
    assert resolve_codec_spec(auto, "GRPC") == "q8"
    assert resolve_codec_spec(auto, "LOOPBACK") is None
    # invalid specs are rejected at config time
    with pytest.raises(ValueError):
        resolve_codec_spec(SimpleNamespace(comm_codec="lz77"))


def test_comm_quantize_shim_warns_once(caplog):
    codec_mod._quantize_warned = False
    args = SimpleNamespace(comm_quantize=True)
    with caplog.at_level(logging.WARNING):
        assert resolve_codec_spec(args) == "q8"
        assert resolve_codec_spec(args) == "q8"
    warned = [r for r in caplog.records
              if "comm_quantize is deprecated" in r.getMessage()]
    assert len(warned) == 1


def test_downlink_projection_is_stateless():
    assert downlink_spec("delta|topk:0.01|q8") == "q8"
    assert downlink_spec("delta|topk:0.01|q4") == "q4"
    assert downlink_spec("delta") is None
    assert downlink_spec(None) is None
    # explicit override: quant-only accepted, stateful stages rejected
    assert resolve_downlink_spec(
        SimpleNamespace(comm_codec_downlink="q4"), "delta|topk:0.01|q8") == "q4"
    assert resolve_downlink_spec(
        SimpleNamespace(comm_codec_downlink="none"), "q8") is None
    assert resolve_downlink_spec(
        SimpleNamespace(comm_codec_downlink="auto"), "delta|topk:0.1|q8") == "q8"
    with pytest.raises(ValueError):
        resolve_downlink_spec(
            SimpleNamespace(comm_codec_downlink="topk:0.1|q8"), "q8")


# --------------------------------------------------- quantization oracles

def test_quant_error_bound_per_chunk():
    rng = np.random.default_rng(0)
    vals = (rng.standard_normal(1024) * 3.0).astype(np.float32)
    for bits, denom in ((8, 32.0), (4, 2.0)):
        q, s, dec = stochastic_quantize(vals, bits, 1, 2, 3)
        assert q.dtype == np.int8 and abs(int(q.max())) <= {8: 127, 4: 7}[bits]
        # pow2 scale: s = 2^(ea-eb) <= 2*amax/2^eb, and one stochastic
        # rounding step contributes < 1 level of error
        err = np.abs(dec - vals).reshape(4, 256)
        amax = np.abs(vals.reshape(4, 256)).max(axis=1)
        assert (err.max(axis=1) <= amax / denom).all()
        np.testing.assert_array_equal(dec, dequantize(q, s, vals.size))


def test_quant_unbiased_on_flat_block():
    # stochastic rounding of a constant mid-level value averages back to it
    v = np.full(4096, 0.3, np.float32)
    _, _, dec = stochastic_quantize(v, 8, 9, 0, 0)
    assert abs(float(dec.mean()) - 0.3) < 1e-3
    assert set(np.round(np.unique(dec / dec.min())).astype(int)) <= {1, 2}


def test_stochastic_rounding_deterministic_per_key():
    rng = np.random.default_rng(1)
    vals = rng.standard_normal(512).astype(np.float32)
    a = stochastic_quantize(vals, 8, 7, 3, 11)
    b = stochastic_quantize(vals, 8, 7, 3, 11)
    np.testing.assert_array_equal(a[0], b[0])  # same key -> same bytes
    for other in ((8, 3, 11), (7, 4, 11), (7, 3, 12)):  # seed/round/client
        c = stochastic_quantize(vals, 8, *other)
        assert (a[0] != c[0]).any()
    d = stochastic_quantize(vals, 8, 7, 3, 11, leaf_hash=99)
    assert (a[0] != d[0]).any()


def test_int4_pack_roundtrip_odd_length():
    rng = np.random.default_rng(2)
    q = rng.integers(-7, 8, size=33).astype(np.int8)
    packed = pack_int4(q)
    assert packed.dtype == np.uint8 and packed.size == 17
    np.testing.assert_array_equal(unpack_int4(packed, 33), q)


def test_delta_terminal_roundtrip_exact():
    rng = np.random.default_rng(3)
    base = {"w": rng.standard_normal(128).astype(np.float32),
            "b": rng.standard_normal(100).astype(np.float32)}
    tree = {"w": base["w"] + np.float32(1e-3) * rng.standard_normal(128).astype(np.float32),
            "b": base["b"] * np.float32(0.5)}
    frame = encode_tree(tree, "delta", base=base)
    assert is_codec_frame(frame)
    out = decode_tree(frame, base=base)
    # f64 carrier makes decode(encode(x)) bit-exact for float32 inputs
    np.testing.assert_array_equal(out["w"], tree["w"])
    np.testing.assert_array_equal(out["b"], tree["b"])
    assert out["w"].dtype == np.float32
    with pytest.raises(ValueError):
        decode_tree(frame)  # delta frames need the base


def test_dtype_restored_through_both_codecs():
    import ml_dtypes

    tree = {"w64": np.linspace(-1.0, 1.0, 256).astype(np.float64),
            "w32": np.linspace(-2.0, 2.0, 256).astype(np.float32),
            "bf": np.full((128,), 1.5, ml_dtypes.bfloat16),
            "steps": np.arange(10, dtype=np.int32)}
    # legacy int8 frame (the pre-codec wire format): dtype token rides along
    legacy = decompress_tree(compress_tree({k: tree[k] for k in ("w64", "w32", "steps")}))
    assert legacy["w64"].dtype == np.float64
    assert legacy["w32"].dtype == np.float32
    np.testing.assert_array_equal(legacy["steps"], tree["steps"])
    np.testing.assert_allclose(legacy["w64"], tree["w64"], atol=1.0 / 32)
    # pipeline frame
    out = decode_tree(encode_tree(tree, "q8", seed=5))
    assert out["w64"].dtype == np.float64
    assert out["bf"].dtype == np.dtype(ml_dtypes.bfloat16)
    assert out["steps"].dtype == np.int32
    np.testing.assert_allclose(out["w32"], tree["w32"], atol=2.0 / 32)


def test_topk_ef_converges_where_plain_topk_stalls():
    """Minimize 0.5*||x - t||^2 with compressed gradients: error feedback
    must drive the iterate into the target; the same spec without residual
    carry is stuck with its truncation bias."""
    rng = np.random.default_rng(4)
    t = rng.standard_normal(512).astype(np.float32)

    def descend(residuals):
        codec = UpdateCodec("topk:0.05|q8")
        x = np.zeros_like(t)
        # lr must respect the EF delay (~1/rho rounds between visits to a
        # coordinate): lr * delay < 2 or the replayed residual overshoots
        for r in range(200):
            g = {"g": x - t}
            ghat = codec.decode(codec.encode(
                g, seed=0, round_idx=r, client_id=0, residuals=residuals))["g"]
            x = x - np.float32(0.05) * ghat
        return float(np.linalg.norm(x - t) / np.linalg.norm(t))

    err_ef = descend({})
    err_plain = descend(None)
    assert err_ef < 1e-3
    assert err_plain > 0.1


def test_wire_nbytes_estimate_matches_frames():
    rng = np.random.default_rng(5)
    tree = {"layer": {"w": rng.standard_normal(300).astype(np.float32)},
            "bias": rng.standard_normal(10).astype(np.float32)}
    for spec in ("q8", "q4", "topk:0.1|q8", "delta|topk:0.1", "delta"):
        frame = encode_tree(tree, spec, seed=1)
        raw, coded = spec_wire_nbytes(spec, tree)
        assert raw == tree_nbytes(tree)
        assert coded == frame_nbytes(frame), spec
    raw, coded = spec_wire_nbytes("delta|topk:0.01|q8", tree)
    assert coded < raw / 10  # the acceptance-spec frame is >10x smaller


# ------------------------------------------------- numpy <-> JAX bit parity

def test_stacked_roundtrip_bit_parity_with_wire_codec():
    """The simulator's batched JAX codec and the numpy wire codec must agree
    BIT-exactly per client, including residual carry across rounds."""
    import jax.numpy as jnp

    rng = np.random.default_rng(6)
    C, cids, seed = 3, np.array([5, 9, 2], np.uint32), 13
    w = rng.standard_normal((C, 300)).astype(np.float32)
    b = rng.standard_normal((C, 10)).astype(np.float32)
    for spec in ("q8", "q4", "topk:0.1|q8", "delta|topk:0.05|q8"):
        cs = parse_codec_spec(spec)
        rt = build_stacked_roundtrip(spec, seed)
        codec = UpdateCodec(spec)
        res_np = [{} for _ in range(C)]
        res_jax = ({"layer": {"w": jnp.zeros((C, 300), jnp.float32)},
                    "bias": jnp.zeros((C, 10), jnp.float32)}
                   if cs.topk is not None else ())
        for rnd in range(2):
            upd = {"layer": {"w": jnp.asarray(w * np.float32(1 + rnd))},
                   "bias": jnp.asarray(b)}
            dec_jax, res_jax = rt(upd, res_jax,
                                  jnp.asarray(cids), jnp.uint32(rnd))
            for c in range(C):
                tree_c = {"layer": {"w": w[c] * np.float32(1 + rnd)},
                          "bias": b[c]}
                dec_np = codec.decode(codec.encode(
                    tree_c, seed=seed, round_idx=rnd, client_id=int(cids[c]),
                    residuals=res_np[c] if cs.topk is not None else None))
                np.testing.assert_array_equal(
                    np.asarray(dec_jax["layer"]["w"])[c],
                    dec_np["layer"]["w"], err_msg=f"{spec} round {rnd}")
                np.testing.assert_array_equal(
                    np.asarray(dec_jax["bias"])[c], dec_np["bias"])
                if cs.topk is not None:
                    np.testing.assert_array_equal(
                        np.asarray(res_jax["layer"]["w"])[c],
                        res_np[c]["layer/w"], err_msg=f"{spec} round {rnd}")
        if cs.topk is None:
            assert res_jax == ()  # untouched when no error feedback


# ------------------------------------------------------------- wire parity

def _sample_frame():
    rng = np.random.default_rng(7)
    tree = {"dense": {"kernel": rng.standard_normal((20, 15)).astype(np.float32)},
            "bias": rng.standard_normal(10).astype(np.float32)}
    frame = encode_tree(tree, "delta|topk:0.1|q8", seed=3, round_idx=1,
                        client_id=2)
    return frame, tree


def _assert_frame_equal(got, frame):
    assert is_codec_frame(got)
    assert got["spec"] == frame["spec"]
    assert set(got["leaves"]) == set(frame["leaves"])
    for path, rec in frame["leaves"].items():
        grec = got["leaves"][path]
        for key in ("q", "s", "idx", "v", "raw"):
            assert (key in rec) == (key in grec)
            if key in rec:
                a, b = np.asarray(rec[key]), np.asarray(grec[key])
                assert a.dtype == b.dtype, (path, key)
                np.testing.assert_array_equal(a, b, err_msg=f"{path}/{key}")
    # and the received frame decodes to the same tree
    a, b = decode_tree(frame), decode_tree(got)
    np.testing.assert_array_equal(a["dense"]["kernel"], b["dense"]["kernel"])


def _roundtrip_via(m_send, m_recv, frame):
    received = []

    class _Obs:
        def receive_message(self, t, m):
            received.append(m.get(Message.MSG_ARG_KEY_MODEL_PARAMS))

    msg = Message(3, m_send.rank, m_recv.rank)
    msg.add_params(Message.MSG_ARG_KEY_MODEL_PARAMS, frame)
    m_send.send_message(msg)
    m_recv.add_observer(_Obs())
    t = threading.Thread(target=m_recv.handle_receive_message, daemon=True)
    t.start()
    deadline = time.time() + 10
    while not received and time.time() < deadline:
        time.sleep(0.01)
    m_send.stop_receive_message()
    m_recv.stop_receive_message()
    t.join(timeout=5)
    assert received, "frame never arrived"
    return received[0]


def test_codec_frame_parity_loopback():
    frame, _ = _sample_frame()
    hub = LoopbackHub()
    m0 = LoopbackCommManager(0, 2, hub)
    m1 = LoopbackCommManager(1, 2, hub)
    _assert_frame_equal(_roundtrip_via(m0, m1, frame), frame)


def test_codec_frame_parity_grpc():
    pytest.importorskip("grpc")
    from fedml_tpu.comm.grpc_backend import GRPCCommManager

    frame, _ = _sample_frame()
    m0 = GRPCCommManager(rank=0, size=2, base_port=21890)
    m1 = GRPCCommManager(rank=1, size=2, base_port=21890)
    try:
        _assert_frame_equal(_roundtrip_via(m0, m1, frame), frame)
    finally:
        m0.stop_receive_message()
        m1.stop_receive_message()


def test_codec_frame_parity_mqtt_s3():
    frame, _ = _sample_frame()
    broker = InProcessBroker()
    store = InMemoryBlobStore()
    m0 = MqttS3CommManager(broker, store, rank=0, size=2, run_id="codec")
    m1 = MqttS3CommManager(broker, store, rank=1, size=2, run_id="codec")
    _assert_frame_equal(_roundtrip_via(m0, m1, frame), frame)


def test_codec_frame_parity_trpc():
    from fedml_tpu.comm.trpc_backend import TRPCCommManager

    frame, _ = _sample_frame()
    m0 = TRPCCommManager(rank=0, size=2, base_port=21990)
    m1 = TRPCCommManager(rank=1, size=2, base_port=21990)
    try:
        _assert_frame_equal(_roundtrip_via(m0, m1, frame), frame)
    finally:
        m0.stop_receive_message()
        m1.stop_receive_message()


def test_unset_codec_plain_wire_roundtrip():
    """With no codec configured the wire carries the raw tree: same bytes as
    a build without comm/codec.py (nothing marks, wraps, or re-encodes it)."""
    rng = np.random.default_rng(8)
    tree = {"w": rng.standard_normal(200).astype(np.float32),
            "b64": rng.standard_normal(70),
            "n": np.int64(3)}
    msg = Message(3, 1, 0)
    msg.add_params(Message.MSG_ARG_KEY_MODEL_PARAMS, tree)
    data = msg.to_bytes()
    got = Message.from_bytes(data).get(Message.MSG_ARG_KEY_MODEL_PARAMS)
    assert not is_codec_frame(got)
    for k in ("w", "b64"):
        assert got[k].dtype == tree[k].dtype
        np.testing.assert_array_equal(got[k], tree[k])
    # byte-stability: packing the same message twice is deterministic
    msg2 = Message(3, 1, 0)
    msg2.add_params(Message.MSG_ARG_KEY_MODEL_PARAMS, tree)
    assert msg2.to_bytes() == data


# ----------------------------------------------- end-to-end acceptance gate

@pytest.fixture()
def _fresh_telemetry():
    telemetry.configure(enabled=True, reset=True)
    yield
    telemetry.configure(enabled=True, reset=True)


def test_cross_silo_codec_accuracy_within_2pct_at_10x(_fresh_telemetry):
    """ISSUE acceptance: the chaos-drill topology (fault-free here) under
    ``delta|topk:0.01|q8`` must land within 2%% of the uncompressed run's
    final eval accuracy while moving >=10x fewer uplink bytes."""
    from fedml_tpu.cross_silo.chaos import run_chaos_drill

    common = dict(comm_round=25, fault_drop_rate=0.0, fault_seed=0,
                  frequency_of_the_test=25)

    def final_acc(history):
        for rec in reversed(history):
            if "test_acc" in rec:
                return float(rec["test_acc"])
        raise AssertionError("no eval in drill history")

    clean = run_chaos_drill(**common)
    assert clean.ok
    coded = run_chaos_drill(comm_codec="delta|topk:0.01|q8", **common)
    assert coded.ok
    assert abs(final_acc(coded.history) - final_acc(clean.history)) <= 0.02
    assert coded.codec_ratio("uplink") >= 10.0
    assert coded.codec_bytes_wire["uplink"] > 0


def test_chaos_drill_absorbs_faults_on_compressed_frames(_fresh_telemetry):
    """chaos-drill --codec: drop/duplicate faults on codec frames are
    absorbed by the resilience plane and the codec counters populate."""
    from fedml_tpu.cross_silo.chaos import run_chaos_drill

    res = run_chaos_drill(comm_codec="delta|topk:0.05|q8",
                          fault_duplicate_rate=0.1)
    assert res.ok, res.summary()
    assert sum(res.faults_injected.values()) > 0
    assert res.codec_bytes_wire.get("uplink", 0) > 0
    assert res.codec_bytes_wire.get("downlink", 0) > 0  # q8 broadcast leg
    assert res.codec_ratio("uplink") > res.codec_ratio("downlink") >= 2.0
    assert "codec uplink" in res.summary()


def test_chaos_drill_byzantine_on_compressed_frames(_fresh_telemetry):
    """decompress-then-corrupt: a NaN byzantine silo corrupts the DECODED
    update, so the sanitizer quarantines it exactly as on raw frames while
    every honest update still travels compressed."""
    import numpy as _np

    from fedml_tpu.cross_silo.chaos import run_chaos_drill

    res = run_chaos_drill(
        comm_codec="delta|topk:0.05|q8",
        fault_byzantine_kind="nan", fault_byzantine_ranks=[2],
        sanitize_updates=True, fault_drop_rate=0.0,
        local_test_on_all_clients=True, comm_round=3,
        client_num_in_total=4, client_num_per_round=4)
    assert res.ok, res.summary()
    assert res.quarantined >= 3, res.summary()
    assert res.codec_bytes_wire.get("uplink", 0) > 0
    for h in res.history:
        assert h["quarantined"] == [2], h
        assert _np.isfinite(h["local_train_loss"]), h


def test_simulator_codec_off_is_bit_identical(_fresh_telemetry):
    """comm_codec unset and comm_codec="none" run the exact same round step
    as a pre-codec build; an explicit spec adds a codec phase to telemetry."""
    from fedml_tpu.simulation import build_simulator

    def run(**kw):
        base = dict(dataset="mnist", model="lr", debug_small_data=True,
                    client_num_in_total=3, client_num_per_round=3,
                    comm_round=2, learning_rate=0.1, epochs=1, batch_size=8,
                    frequency_of_the_test=1, random_seed=0, prefetch=False)
        base.update(kw)
        sim, apply_fn = build_simulator(fedml_tpu.init(config=base))
        return sim.run(apply_fn, log_fn=None)

    h_unset = run()
    h_none = run(comm_codec="none")
    accs = [r["test_acc"] for r in h_unset if "test_acc" in r]
    assert accs == [r["test_acc"] for r in h_none if "test_acc" in r]
    h_codec = run(comm_codec="q8")
    codec_phase = sum(r.get("phases", {}).get("codec", 0.0)
                     for r in h_codec if "phases" in r)
    assert codec_phase > 0.0
    counters = telemetry.get_registry().snapshot()["counters"]
    key = "fedml_codec_bytes_out{direction=encode,plane=uplink}"
    assert counters.get(key, 0.0) > 0.0


def test_codec_sweep_bench_smoke(_fresh_telemetry):
    import bench

    rc = bench.codec_sweep_bench(specs=("q8", "delta|topk:0.05|q8"), rounds=2)
    assert rc == 0
