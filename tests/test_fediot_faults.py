"""FedIoT anomaly detection + client-dropout fault injection + tracing."""

import jax
import jax.numpy as jnp
import numpy as np

import fedml_tpu
from fedml_tpu.simulation import build_simulator
from fedml_tpu.simulation.fed_sim import FedSimulator, SimConfig


def test_fediot_autoencoder_detects_anomalies():
    from fedml_tpu.algorithms.fediot import (
        anomaly_scores,
        detection_threshold,
        get_fediot_algorithm,
    )
    from fedml_tpu.data.federated import ArrayPair, build_federated_data
    from fedml_tpu.core.partition import homo_partition
    from fedml_tpu.models.autoencoder import AnomalyAutoencoder

    rng = np.random.default_rng(0)
    d = 20
    # benign traffic lives on a low-dim manifold; anomalies are off-manifold
    basis = rng.normal(size=(4, d)).astype(np.float32)
    benign = (rng.normal(size=(600, 4)).astype(np.float32) @ basis)
    anomalous = rng.normal(size=(100, d)).astype(np.float32) * 3.0
    train = ArrayPair(benign[:500], np.zeros(500, np.int32))
    test = ArrayPair(benign[500:], np.zeros(100, np.int32))
    np.random.seed(0)
    fed = build_federated_data(train, test, homo_partition(500, 4), 2)

    model = AnomalyAutoencoder(input_dim=d, hidden=(16, 8))

    def apply_fn(params, x, train=False, rngs=None):
        return model.apply(params, x)

    variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, d)))
    alg = get_fediot_algorithm(apply_fn, lr=5e-3, epochs=2)
    sim = FedSimulator(
        fed, alg, variables,
        SimConfig(comm_round=12, client_num_in_total=4, client_num_per_round=4,
                  batch_size=32, frequency_of_the_test=100),
    )
    sim.run(apply_fn=None, log_fn=None)

    benign_scores = anomaly_scores(apply_fn, sim.params, jnp.asarray(test.x))
    anom_scores = anomaly_scores(apply_fn, sim.params, jnp.asarray(anomalous))
    thresh = detection_threshold(benign_scores, k_sigma=3.0)
    tpr = float((anom_scores > thresh).mean())
    fpr = float((benign_scores > thresh).mean())
    assert tpr > 0.9, (tpr, fpr)
    assert fpr < 0.2


def test_client_dropout_fault_injection_still_learns():
    args = fedml_tpu.init(config=dict(
        dataset="mnist", model="lr", debug_small_data=True,
        client_num_in_total=10, client_num_per_round=8, comm_round=6,
        learning_rate=0.1, batch_size=16, frequency_of_the_test=5,
        random_seed=0, client_dropout_rate=0.4,
    ))
    sim, apply_fn = build_simulator(args)
    hist = sim.run(apply_fn, log_fn=None)
    # training survives 40% client crashes per round
    assert hist[-1]["test_acc"] > 0.6
    assert np.isfinite(hist[-1]["train_loss"])


def test_cross_silo_tracing_emits_spans(tmp_path):
    import threading

    from fedml_tpu.comm import LoopbackHub
    from fedml_tpu.cross_silo import FedML_Horizontal

    args = fedml_tpu.init(config=dict(
        dataset="mnist", model="lr", debug_small_data=True,
        client_num_in_total=2, client_num_per_round=2, comm_round=2,
        learning_rate=0.1, batch_size=8, frequency_of_the_test=1,
        random_seed=0, enable_tracking=True,
    ))
    hub = LoopbackHub()
    server = FedML_Horizontal(args, 0, 2, backend="LOOPBACK", hub=hub)
    clients = [FedML_Horizontal(args, r, 2, backend="LOOPBACK", hub=hub) for r in (1, 2)]
    threads = [threading.Thread(target=c.run, daemon=True) for c in clients]
    for t in threads:
        t.start()
    server.start()
    server.run()
    for t in threads:
        t.join(timeout=30)
    spans = server.mlops_event.sink.records
    kinds = [r["kind"] for r in spans]
    assert kinds.count("event_started") == 2 and kinds.count("event_ended") == 2
    assert all(r["event"] == "server.agg_and_eval" for r in spans)
