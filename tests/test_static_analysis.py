"""Tier-1: the graftcheck static-analysis suite (fedml_tpu/analysis/).

Three layers:

1. the package itself must be clean — zero non-baselined findings from
   every checker (the committed baseline in
   scripts/graftcheck_baseline.json grandfathers the known, deliberate
   exceptions, and deleting any of its lines must turn the run red);
2. every checker must actually FIRE on its bad fixture and stay silent
   on its clean twin (tests/fixtures/graftcheck/);
3. the shared machinery — suppression comments, baseline round-trip,
   CLI entry point — must keep its contract.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

from fedml_tpu.analysis import core as gc
from fedml_tpu.analysis.config_drift import ConfigDriftChecker
from fedml_tpu.analysis.determinism import DeterminismChecker
from fedml_tpu.analysis.jit_purity import JitPurityChecker
from fedml_tpu.analysis.lock_order import LockOrderChecker
from fedml_tpu.analysis.no_print import NoPrintChecker

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO_ROOT, "tests", "fixtures", "graftcheck")


def _run_on_fixture(checker_cls, filename, relpath=None):
    """One checker over one fixture file; ``relpath`` lets a fixture
    masquerade as an in-scope module for scope-restricted checkers."""
    path = os.path.join(FIXTURES, filename)
    ctx = gc.Context(repo_root=FIXTURES, package_dir=path)
    mod = gc.load_module(path, FIXTURES)
    if relpath is not None:
        mod.relpath = relpath
    checker = checker_cls(ctx)
    findings = []
    if checker.interested(mod.relpath):
        findings.extend(checker.visit_module(mod))
    findings.extend(checker.finalize())
    return findings


# ------------------------------------------------------- package is clean

def test_package_has_no_new_findings():
    rc = gc.main([])
    assert rc == 0, "graftcheck found non-baselined violations in fedml_tpu/"


def test_analyze_runs_fast_enough():
    # the <30s CPU budget from the adoption contract; generous margin so
    # CI noise never flakes this
    import time

    t0 = time.perf_counter()
    gc.main([])
    assert time.perf_counter() - t0 < 30.0


def test_deleting_a_baseline_line_fails_the_run(tmp_path):
    baseline_path = gc.default_baseline_path(REPO_ROOT)
    baseline = gc.load_baseline(baseline_path)
    assert baseline, "committed baseline should grandfather known findings"
    pruned = tmp_path / "baseline.json"
    pruned.write_text(json.dumps(baseline[1:]))
    rc = gc.main(["--baseline", str(pruned)])
    assert rc == 1, "a de-baselined known finding must turn the run red"


# ------------------------------------------------------------- jit-purity

def test_jit_purity_fires_on_bad_fixture():
    findings = _run_on_fixture(JitPurityChecker, "jit_purity_bad.py")
    msgs = "\n".join(f.message for f in findings)
    assert "time.time" in msgs          # direct impure call in jit body
    assert "print" in msgs              # host I/O in jit body
    assert "random.random" in msgs      # unkeyed python RNG in jit body
    assert "np.random" in msgs          # reached through the call graph
    assert "time.monotonic" in msgs     # inside a lax.scan body
    assert all(f.checker == "jit-purity" for f in findings)


def test_jit_purity_silent_on_clean_fixture():
    assert _run_on_fixture(JitPurityChecker, "jit_purity_clean.py") == []


# ----------------------------------------------------------- determinism

def test_determinism_fires_on_bad_fixture():
    findings = _run_on_fixture(DeterminismChecker, "determinism_bad.py")
    keys = {f.key for f in findings}
    assert "make_rng:unseeded:default_rng" in keys
    assert "make_py_rng:unseeded:Random" in keys
    assert "time_seeded:time-seed:default_rng" in keys
    assert "reseed_global:global-seed" in keys
    assert "cohort_order:set-order" in keys
    assert "spec_leaf_order:set-order" in keys
    assert "quantize_without_seed:stochastic-unseeded:stochastic_quantize" in keys
    assert "quantize_none_seed:stochastic-unseeded:stochastic_quantize" in keys
    assert "key_time_seed:time-seed:stochastic_key" in keys
    assert "roundtrip_without_seed:stochastic-unseeded:build_stacked_roundtrip" \
        in keys


def test_determinism_silent_on_clean_fixture():
    assert _run_on_fixture(DeterminismChecker, "determinism_clean.py") == []


# ------------------------------------------------------------ lock-order

_IN_SCOPE = "fedml_tpu/comm/_graftcheck_fixture.py"


def test_lock_order_fires_on_bad_fixture():
    findings = _run_on_fixture(
        LockOrderChecker, "lock_order_bad.py", relpath=_IN_SCOPE)
    msgs = "\n".join(f.message for f in findings)
    assert "re-acquired" in msgs                       # self-deadlock
    assert "lock acquisition cycle" in msgs            # AB/BA cycle
    assert ".sendall()" in msgs                        # blocking under lock
    assert "time.sleep" in msgs


def test_lock_order_silent_on_clean_fixture():
    findings = _run_on_fixture(
        LockOrderChecker, "lock_order_clean.py", relpath=_IN_SCOPE)
    assert findings == []


def test_lock_order_ignores_out_of_scope_files():
    # same bad source, but outside comm/cross_silo/telemetry scope
    findings = _run_on_fixture(LockOrderChecker, "lock_order_bad.py")
    assert findings == []


# ---------------------------------------------------------- config-drift

def test_config_drift_fires_on_fixture_repo():
    repo = os.path.join(FIXTURES, "config_drift_repo")
    findings = gc.run_checkers(
        [ConfigDriftChecker], os.path.join(repo, "pkg"), repo)
    keys = {f.key for f in findings}
    assert "conflicting-default:retry_count" in keys   # 0 vs 3
    assert "doc-only:ghost_key" in keys                # documented, unread
    assert "undocumented:batch_size" in keys           # read, undocumented
    # None probes and fallback-chain inner defaults never conflict
    assert "conflicting-default:learning_rate" not in keys
    assert "conflicting-default:retry_window" not in keys
    # phase-name drift: emitted-but-undocumented fires, documented is clean
    assert "phase-undocumented:mystery_phase" in keys
    assert "phase-undocumented:warp" not in keys


# -------------------------------------------------------------- no-print

def test_no_print_fires_on_bad_fixture():
    findings = _run_on_fixture(NoPrintChecker, "no_print_bad.py")
    assert len(findings) == 1
    assert findings[0].checker == "no-print"


def test_no_print_silent_on_clean_fixture():
    # logging calls and print-as-value (log_fn=print) stay legal
    assert _run_on_fixture(NoPrintChecker, "no_print_clean.py") == []


def test_no_print_respects_allowlist():
    checker = NoPrintChecker(gc.Context(repo_root=REPO_ROOT,
                                        package_dir=REPO_ROOT))
    assert not checker.interested("fedml_tpu/cli/main.py")
    assert not checker.interested("fedml_tpu/utils/chip_probe.py")
    assert checker.interested("fedml_tpu/core/telemetry.py")


# ----------------------------------------------------------- suppression

def _no_print_over(tmp_path, source):
    path = tmp_path / "mod.py"
    path.write_text(source)
    return gc.run_checkers([NoPrintChecker], str(path), str(tmp_path))


def test_inline_suppression_drops_finding(tmp_path):
    src = 'print("x")  # graftcheck: disable=no-print\n'
    assert _no_print_over(tmp_path, src) == []


def test_standalone_comment_suppresses_next_line(tmp_path):
    src = ('# tooling speaks over stdout; graftcheck: disable=no-print\n'
           'print("x")\n')
    assert _no_print_over(tmp_path, src) == []


def test_disable_all_suppresses_every_checker(tmp_path):
    src = 'print("x")  # graftcheck: disable=all\n'
    assert _no_print_over(tmp_path, src) == []


def test_unsuppressed_line_still_fires(tmp_path):
    src = ('print("a")  # graftcheck: disable=no-print\n'
           'print("b")\n')
    findings = _no_print_over(tmp_path, src)
    assert [f.line for f in findings] == [2]


def test_suppression_for_other_checker_does_not_apply(tmp_path):
    src = 'print("x")  # graftcheck: disable=determinism\n'
    findings = _no_print_over(tmp_path, src)
    assert len(findings) == 1


# -------------------------------------------------------------- baseline

def test_baseline_round_trip(tmp_path):
    findings = _run_on_fixture(DeterminismChecker, "determinism_bad.py")
    assert findings
    path = tmp_path / "baseline.json"
    gc.write_baseline(findings, str(path))
    baseline = gc.load_baseline(str(path))
    new, old, stale = gc.apply_baseline(findings, baseline)
    assert new == [] and stale == []
    assert {f.fingerprint for f in old} == set(baseline)

    # dropping one entry resurfaces exactly that finding as new
    new, _old, _stale = gc.apply_baseline(findings, baseline[1:])
    assert [f.fingerprint for f in new] == [baseline[0]]

    # a fingerprint matching nothing is reported stale, never fatal
    _new, _old, stale = gc.apply_baseline(findings, baseline + ["bogus:x:y"])
    assert stale == ["bogus:x:y"]


def test_baseline_file_is_one_fingerprint_per_line(tmp_path):
    findings = _run_on_fixture(NoPrintChecker, "no_print_bad.py")
    path = tmp_path / "baseline.json"
    gc.write_baseline(findings, str(path))
    lines = path.read_text().splitlines()
    # [ ... one quoted fingerprint per interior line ... ]
    assert lines[0] == "[" and lines[-1] == "]"
    assert len(lines) == 2 + len({f.fingerprint for f in findings})


def test_fingerprints_are_line_number_free():
    findings = _run_on_fixture(NoPrintChecker, "no_print_bad.py")
    for f in findings:
        assert str(f.line) not in f.fingerprint.split(":")[-1] or f.line > 99


# --------------------------------------------------------------- frontend

def test_cli_analyze_exits_zero():
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-m", "fedml_tpu.cli", "analyze"],
        cwd=REPO_ROOT, env=env, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "graftcheck:" in proc.stdout


def test_json_output_shape(tmp_path, capsys):
    bad = os.path.join(FIXTURES, "no_print_bad.py")
    rc = gc.main(["--json", "--no-baseline", "--checker", "no-print",
                  "--root", bad])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert out["checkers"] == ["no-print"]
    assert len(out["new"]) == 1
    finding = out["new"][0]
    assert set(finding) == {"checker", "path", "line", "severity",
                            "message", "fingerprint"}


def test_checker_registry_is_complete():
    assert sorted(gc.checker_registry()) == [
        "config-drift", "determinism", "jit-purity", "lock-order", "no-print"]
