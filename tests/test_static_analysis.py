"""Tier-1: the graftcheck static-analysis suite (fedml_tpu/analysis/).

Three layers:

1. the package itself must be clean — zero non-baselined findings from
   every checker (the committed baseline in
   scripts/graftcheck_baseline.json grandfathers the known, deliberate
   exceptions, and deleting any of its lines must turn the run red);
2. every checker must actually FIRE on its bad fixture and stay silent
   on its clean twin (tests/fixtures/graftcheck/);
3. the shared machinery — suppression comments, baseline round-trip,
   CLI entry point — must keep its contract.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

from fedml_tpu.analysis import core as gc
from fedml_tpu.analysis.collective_deadlock import CollectiveDeadlockChecker
from fedml_tpu.analysis.config_drift import ConfigDriftChecker
from fedml_tpu.analysis.determinism import DeterminismChecker
from fedml_tpu.analysis.donation import DonationSafetyChecker
from fedml_tpu.analysis.host_sync import HostSyncChecker
from fedml_tpu.analysis.jit_purity import JitPurityChecker
from fedml_tpu.analysis.lock_order import LockOrderChecker
from fedml_tpu.analysis.no_print import NoPrintChecker
from fedml_tpu.analysis.resource_leak import ResourceLeakChecker
from fedml_tpu.analysis.retrace_hazard import RetraceHazardChecker
from fedml_tpu.analysis.sharding_consistency import ShardingConsistencyChecker
from fedml_tpu.analysis.thread_hazard import ThreadHazardChecker
from fedml_tpu.analysis.wire_protocol import WireProtocolChecker

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO_ROOT, "tests", "fixtures", "graftcheck")


def _run_on_fixture(checker_cls, filename, relpath=None):
    """One checker over one fixture file; ``relpath`` lets a fixture
    masquerade as an in-scope module for scope-restricted checkers."""
    path = os.path.join(FIXTURES, filename)
    ctx = gc.Context(repo_root=FIXTURES, package_dir=path)
    mod = gc.load_module(path, FIXTURES)
    if relpath is not None:
        mod.relpath = relpath
    checker = checker_cls(ctx)
    findings = []
    if checker.interested(mod.relpath):
        findings.extend(checker.visit_module(mod))
    findings.extend(checker.finalize())
    return findings


# ------------------------------------------------------- package is clean

def test_package_has_no_new_findings():
    rc = gc.main([])
    assert rc == 0, "graftcheck found non-baselined violations in fedml_tpu/"


def test_analyze_runs_fast_enough():
    # the <60s CPU budget for the full ten-checker run; generous margin so
    # CI noise never flakes this
    import time

    t0 = time.perf_counter()
    gc.main([])
    assert time.perf_counter() - t0 < 60.0


def test_deleting_a_baseline_line_fails_the_run(tmp_path):
    baseline_path = gc.default_baseline_path(REPO_ROOT)
    baseline = gc.load_baseline(baseline_path)
    assert baseline, "committed baseline should grandfather known findings"
    pruned = tmp_path / "baseline.json"
    pruned.write_text(json.dumps(baseline[1:]))
    rc = gc.main(["--baseline", str(pruned)])
    assert rc == 1, "a de-baselined known finding must turn the run red"


# ------------------------------------------------------------- jit-purity

def test_jit_purity_fires_on_bad_fixture():
    findings = _run_on_fixture(JitPurityChecker, "jit_purity_bad.py")
    msgs = "\n".join(f.message for f in findings)
    assert "time.time" in msgs          # direct impure call in jit body
    assert "print" in msgs              # host I/O in jit body
    assert "random.random" in msgs      # unkeyed python RNG in jit body
    assert "np.random" in msgs          # reached through the call graph
    assert "time.monotonic" in msgs     # inside a lax.scan body
    assert all(f.checker == "jit-purity" for f in findings)


def test_jit_purity_silent_on_clean_fixture():
    assert _run_on_fixture(JitPurityChecker, "jit_purity_clean.py") == []


def test_jit_purity_fires_inside_pallas_kernel():
    # a pallas_call kernel body (handed over via functools.partial) is a
    # traced root exactly like a jit body
    findings = _run_on_fixture(JitPurityChecker, "agg_pallas_bad.py")
    keys = {f.key for f in findings}
    assert "_agg_kernel:print" in keys
    assert "_agg_kernel:time.time" in keys
    assert "_agg_kernel:np.random.rand" in keys
    assert "_agg_kernel:.item" in keys
    assert all(f.checker == "jit-purity" for f in findings)


def test_jit_purity_silent_on_clean_pallas_fixture():
    assert _run_on_fixture(JitPurityChecker, "agg_pallas_clean.py") == []


# ----------------------------------------------------------- determinism

def test_determinism_fires_on_bad_fixture():
    findings = _run_on_fixture(DeterminismChecker, "determinism_bad.py")
    keys = {f.key for f in findings}
    assert "make_rng:unseeded:default_rng" in keys
    assert "make_py_rng:unseeded:Random" in keys
    assert "time_seeded:time-seed:default_rng" in keys
    assert "reseed_global:global-seed" in keys
    assert "cohort_order:set-order" in keys
    assert "spec_leaf_order:set-order" in keys
    assert "quantize_without_seed:stochastic-unseeded:stochastic_quantize" in keys
    assert "quantize_none_seed:stochastic-unseeded:stochastic_quantize" in keys
    assert "key_time_seed:time-seed:stochastic_key" in keys
    assert "roundtrip_without_seed:stochastic-unseeded:build_stacked_roundtrip" \
        in keys


def test_determinism_silent_on_clean_fixture():
    assert _run_on_fixture(DeterminismChecker, "determinism_clean.py") == []


# ------------------------------------------------------------ lock-order

_IN_SCOPE = "fedml_tpu/comm/_graftcheck_fixture.py"


def test_lock_order_fires_on_bad_fixture():
    findings = _run_on_fixture(
        LockOrderChecker, "lock_order_bad.py", relpath=_IN_SCOPE)
    msgs = "\n".join(f.message for f in findings)
    assert "re-acquired" in msgs                       # self-deadlock
    assert "lock acquisition cycle" in msgs            # AB/BA cycle
    assert ".sendall()" in msgs                        # blocking under lock
    assert "time.sleep" in msgs


def test_lock_order_silent_on_clean_fixture():
    findings = _run_on_fixture(
        LockOrderChecker, "lock_order_clean.py", relpath=_IN_SCOPE)
    assert findings == []


def test_lock_order_ignores_out_of_scope_files():
    # same bad source, but outside comm/cross_silo/telemetry scope
    findings = _run_on_fixture(LockOrderChecker, "lock_order_bad.py")
    assert findings == []


# ---------------------------------------------------------- config-drift

def test_config_drift_fires_on_fixture_repo():
    repo = os.path.join(FIXTURES, "config_drift_repo")
    findings = gc.run_checkers(
        [ConfigDriftChecker], os.path.join(repo, "pkg"), repo)
    keys = {f.key for f in findings}
    assert "conflicting-default:retry_count" in keys   # 0 vs 3
    assert "doc-only:ghost_key" in keys                # documented, unread
    assert "undocumented:batch_size" in keys           # read, undocumented
    # None probes and fallback-chain inner defaults never conflict
    assert "conflicting-default:learning_rate" not in keys
    assert "conflicting-default:retry_window" not in keys
    # phase-name drift: emitted-but-undocumented fires, documented is clean
    assert "phase-undocumented:mystery_phase" in keys
    assert "phase-undocumented:warp" not in keys


# -------------------------------------------------------------- no-print

def test_no_print_fires_on_bad_fixture():
    findings = _run_on_fixture(NoPrintChecker, "no_print_bad.py")
    assert len(findings) == 1
    assert findings[0].checker == "no-print"


def test_no_print_silent_on_clean_fixture():
    # logging calls and print-as-value (log_fn=print) stay legal
    assert _run_on_fixture(NoPrintChecker, "no_print_clean.py") == []


def test_no_print_respects_allowlist():
    checker = NoPrintChecker(gc.Context(repo_root=REPO_ROOT,
                                        package_dir=REPO_ROOT))
    assert not checker.interested("fedml_tpu/cli/main.py")
    assert not checker.interested("fedml_tpu/utils/chip_probe.py")
    assert checker.interested("fedml_tpu/core/telemetry.py")


# ------------------------------------------------------- donation-safety

def test_donation_safety_fires_on_bad_fixture():
    findings = _run_on_fixture(DonationSafetyChecker, "donation_bad.py")
    keys = {f.key for f in findings}
    # direct self._step = jax.jit(..., donate_argnums=...) binding
    assert "Trainer.step_and_log:use-after-donate:self.params:self._step" in keys
    # builder hop: self._round = self._build_round_step()
    assert "Trainer.advance:use-after-donate:state:self._round" in keys
    # @partial(jax.jit, donate_argnums=...) decorated def, called by name
    assert "drive:use-after-donate:weights:apply_update" in keys
    # inline jax.jit(f, donate_argnums=...)(x) call
    assert "inline:use-after-donate:x:jax.jit" in keys
    assert all(f.checker == "donation-safety" for f in findings)


def test_donation_safety_silent_on_clean_fixture():
    assert _run_on_fixture(DonationSafetyChecker, "donation_clean.py") == []


# -------------------------------------------------- sharding-consistency

def test_sharding_consistency_fires_on_bad_fixture():
    findings = _run_on_fixture(
        ShardingConsistencyChecker, "sharding_consistency_bad.py")
    keys = {f.key for f in findings}
    assert "unknown-axis:clients" in keys          # typo of "client"
    assert "unknown-axis:modle" in keys            # typo inside a tuple spec
    assert "tree-literal-spec" in keys             # hand-rolled spec pytree
    by_key = {f.key: f for f in findings}
    assert by_key["unknown-axis:clients"].severity == "error"
    assert by_key["tree-literal-spec"].severity == "warning"


def test_sharding_consistency_silent_on_clean_fixture():
    # ad-hoc Mesh axis names and the canonical vocabulary are both legal
    assert _run_on_fixture(
        ShardingConsistencyChecker, "sharding_consistency_clean.py") == []


# -------------------------------------------------------------- host-sync

_FED_SIM = "fedml_tpu/simulation/fed_sim.py"


def test_host_sync_fires_on_bad_fixture():
    findings = _run_on_fixture(
        HostSyncChecker, "host_sync_bad.py", relpath=_FED_SIM)
    keys = {f.key for f in findings}
    assert "FedSimulator.run:block_until_ready" in keys
    assert "FedSimulator.run:float()" in keys            # scalar readback
    assert "FedSimulator._round:np.asarray:metrics" in keys
    assert "FedSimulator._round:item:metrics" in keys
    assert "FedSimulator._round:device_get" in keys


def test_host_sync_silent_on_clean_fixture():
    # cold planes (eval/build_*), placement-wrapped asarray, and host
    # containers never fire
    assert _run_on_fixture(
        HostSyncChecker, "host_sync_clean.py", relpath=_FED_SIM) == []


def test_host_sync_ignores_out_of_scope_files():
    findings = _run_on_fixture(HostSyncChecker, "host_sync_bad.py")
    assert findings == []


_PALLAS_MOD = "fedml_tpu/ops/pallas/agg_fixture.py"


def test_host_sync_covers_pallas_op_modules():
    # every top-level def in an ops/pallas module is a hot entry point
    findings = _run_on_fixture(
        HostSyncChecker, "agg_pallas_bad.py", relpath=_PALLAS_MOD)
    keys = {f.key for f in findings}
    assert "fused_agg:block_until_ready" in keys
    assert "fused_agg:np.asarray:out" in keys
    assert "_agg_kernel:item:expr" in keys


def test_host_sync_silent_on_clean_pallas_fixture():
    assert _run_on_fixture(
        HostSyncChecker, "agg_pallas_clean.py", relpath=_PALLAS_MOD) == []


def test_host_sync_pallas_fixture_out_of_scope_by_default():
    assert _run_on_fixture(HostSyncChecker, "agg_pallas_bad.py") == []


def test_host_sync_roots_scanned_round_body():
    # callbacks handed to lax.scan/fori_loop/while_loop are hot even when
    # defined inside a cold _build_* factory (the compiled multi-round
    # dispatch builds its round body exactly that way)
    findings = _run_on_fixture(
        HostSyncChecker, "host_sync_scan_bad.py", relpath=_FED_SIM)
    keys = {f.key for f in findings}
    assert "FedSimulator._build_scan_step.scan_round:np.asarray:out" in keys
    assert "FedSimulator._build_scan_step.scan_round:block_until_ready" in keys
    # call edges OUT of the scanned body are followed
    assert "FedSimulator._round_math:item:loss" in keys
    # fori/while callbacks root the same way
    assert "_build_loops.body_fun:device_get" in keys
    assert "_build_loops.cond_fun:float()" in keys


def test_host_sync_scan_body_why_names_the_hof():
    findings = _run_on_fixture(
        HostSyncChecker, "host_sync_scan_bad.py", relpath=_FED_SIM)
    body = [f for f in findings
            if f.key.startswith("FedSimulator._build_scan_step.scan_round")]
    assert body and all("compiled-region callback" in f.message for f in body)


def test_host_sync_silent_on_clean_scan_fixture():
    # a device-resident scanned body plus host staging in its cold factory
    assert _run_on_fixture(
        HostSyncChecker, "host_sync_scan_clean.py", relpath=_FED_SIM) == []


# ----------------------------------------------------- collective-deadlock

def test_collective_deadlock_fires_on_bad_fixture():
    findings = _run_on_fixture(
        CollectiveDeadlockChecker, "collective_deadlock_bad.py")
    keys = {f.key for f in findings}
    assert "sync_stats:guarded:jax.lax.psum" in keys          # process_index
    assert "rank_guarded:guarded:lax.all_gather" in keys      # *rank* name
    assert "ternary:guarded:lax.pmean" in keys                # IfExp guard
    assert "TenantWorker.maybe_broadcast:guarded:broadcast_one_to_all" in keys


def test_collective_deadlock_silent_on_clean_fixture():
    # uniform guards (config flags, process_count), divergent branches
    # without collectives, and nested defs all stay legal
    assert _run_on_fixture(
        CollectiveDeadlockChecker, "collective_deadlock_clean.py") == []


# ----------------------------------------------------------- thread-hazard

def test_thread_hazard_fires_on_bad_fixture():
    findings = _run_on_fixture(
        ThreadHazardChecker, "thread_hazard_bad.py", relpath=_IN_SCOPE)
    keys = {f.key for f in findings}
    assert "hazard:Wire.status" in keys        # unlocked on both sides
    assert "hazard:Wire._pending" in keys      # reader locks, writer doesn't
    assert "hazard:Pump.result" in keys        # executor.submit thread root


def test_thread_hazard_silent_on_clean_fixture():
    # common lock, entry-lock propagation (self-call and nested plain-name
    # call), const flag flips, and Queue attrs are all safe idioms
    findings = _run_on_fixture(
        ThreadHazardChecker, "thread_hazard_clean.py", relpath=_IN_SCOPE)
    assert findings == []


def test_thread_hazard_ignores_out_of_scope_files():
    findings = _run_on_fixture(ThreadHazardChecker, "thread_hazard_bad.py")
    assert findings == []


# ------------------------------------------- async engine (thread + replay)

_ASYNC_ENGINE = "fedml_tpu/simulation/async_engine.py"


def test_async_engine_scope_fires_on_bad_fixture():
    # the buffered-async module is in thread-hazard scope: an ingest
    # thread folding into the commit buffer without the committer's lock
    # must fire, and so must an unseeded delay-plan RNG (determinism —
    # a replayed straggler schedule would diverge)
    hazards = _run_on_fixture(
        ThreadHazardChecker, "async_engine_bad.py", relpath=_ASYNC_ENGINE)
    keys = {f.key for f in hazards}
    assert "hazard:BadAsyncServer._buffer" in keys
    assert "hazard:BadAsyncServer._version" in keys
    det = _run_on_fixture(
        DeterminismChecker, "async_engine_bad.py", relpath=_ASYNC_ENGINE)
    assert any("default_rng" in f.message for f in det)


def test_async_engine_scope_silent_on_clean_fixture():
    # lock-protected fold/commit + seed-derived RNG stream: both checkers
    # stay quiet, so the real module's discipline is the enforced shape
    assert _run_on_fixture(
        ThreadHazardChecker, "async_engine_clean.py",
        relpath=_ASYNC_ENGINE) == []
    assert _run_on_fixture(
        DeterminismChecker, "async_engine_clean.py",
        relpath=_ASYNC_ENGINE) == []


# ------------------------------------- tiered federation (thread + locks)

_FEDERATION = "fedml_tpu/simulation/federation.py"
_HIERARCHICAL = "fedml_tpu/simulation/hierarchical.py"


def test_federation_scope_fires_on_bad_fixture():
    # the tiered-federation modules are in both checkers' scope: a
    # heartbeat thread reading the round counter the receive handlers
    # write unguarded must fire thread-hazard, and opposite lease/ledger
    # lock nesting on the dispatch vs failover paths must fire lock-order
    hazards = _run_on_fixture(
        ThreadHazardChecker, "federation_bad.py", relpath=_FEDERATION)
    assert "hazard:BadLeafWorker._round" in {f.key for f in hazards}
    locks = _run_on_fixture(
        LockOrderChecker, "federation_bad.py", relpath=_FEDERATION)
    msgs = "\n".join(f.message for f in locks)
    assert "lock acquisition cycle" in msgs
    assert "time.sleep" in msgs


def test_federation_scope_silent_on_clean_fixture():
    # lock-guarded round accessors + a single lease-before-ledger order
    # (sleep outside the critical section): both checkers stay quiet, so
    # the real modules' discipline is the enforced shape
    for relpath in (_FEDERATION, _HIERARCHICAL):
        assert _run_on_fixture(
            ThreadHazardChecker, "federation_clean.py", relpath=relpath) == []
        assert _run_on_fixture(
            LockOrderChecker, "federation_clean.py", relpath=relpath) == []


def test_federation_fixture_out_of_scope_by_default():
    assert _run_on_fixture(ThreadHazardChecker, "federation_bad.py") == []
    assert _run_on_fixture(LockOrderChecker, "federation_bad.py") == []


# --------------------------------------- serving plane (thread + locks)

_SERVING = "fedml_tpu/serving/_graftcheck_fixture.py"


def test_serving_scope_fires_on_bad_fixture():
    # the serving package is in both checkers' scope: a serve-loop
    # thread swapping the active pointer / served-counts the main
    # thread reads unguarded must fire thread-hazard, and a promote
    # that publishes under the store locks (plus AB/BA nesting with
    # the stats path) must fire lock-order
    hazards = _run_on_fixture(
        ThreadHazardChecker, "serving_bad.py", relpath=_SERVING)
    keys = {f.key for f in hazards}
    assert "hazard:BadServer.active" in keys
    assert "hazard:BadServer._served" in keys
    locks = _run_on_fixture(
        LockOrderChecker, "serving_bad.py", relpath=_SERVING)
    msgs = "\n".join(f.message for f in locks)
    assert ".publish()" in msgs
    assert "lock acquisition cycle" in msgs
    assert "time.sleep" in msgs


def test_serving_scope_silent_on_clean_fixture():
    # one short lock around the RCU swap, telemetry after release, the
    # serve thread taking the same lock as readers, Event run flag:
    # both checkers stay quiet, so the real package's discipline is
    # the enforced shape
    assert _run_on_fixture(
        ThreadHazardChecker, "serving_clean.py", relpath=_SERVING) == []
    assert _run_on_fixture(
        LockOrderChecker, "serving_clean.py", relpath=_SERVING) == []


def test_serving_fixture_out_of_scope_by_default():
    assert _run_on_fixture(ThreadHazardChecker, "serving_bad.py") == []
    assert _run_on_fixture(LockOrderChecker, "serving_bad.py") == []


# ------------------------------------ cross-device plane (thread + locks)

_CROSS_DEVICE = "fedml_tpu/cross_device/_graftcheck_fixture.py"


def test_cross_device_scope_fires_on_bad_fixture():
    # the cross-device package is in both checkers' scope: a check-in
    # gateway doing a blocking send under the admission+fleet locks
    # (with the eviction path nesting them in the opposite order) must
    # fire lock-order, and a heartbeat thread stamping last_checkin
    # without the readers' lock must fire thread-hazard
    locks = _run_on_fixture(
        LockOrderChecker, "device_registry_bad.py", relpath=_CROSS_DEVICE)
    msgs = "\n".join(f.message for f in locks)
    assert ".sendall()" in msgs
    assert "time.sleep" in msgs
    assert "lock acquisition cycle" in msgs
    hazards = _run_on_fixture(
        ThreadHazardChecker, "device_registry_bad.py", relpath=_CROSS_DEVICE)
    assert "hazard:Gateway.last_checkin" in {f.key for f in hazards}


def test_cross_device_scope_silent_on_clean_fixture():
    # one nesting order, send/sleep after release, heartbeat writes and
    # staleness reads sharing the fleet lock: both checkers stay quiet,
    # so the real package's discipline is the enforced shape
    assert _run_on_fixture(
        LockOrderChecker, "device_registry_clean.py",
        relpath=_CROSS_DEVICE) == []
    assert _run_on_fixture(
        ThreadHazardChecker, "device_registry_clean.py",
        relpath=_CROSS_DEVICE) == []


def test_cross_device_fixture_out_of_scope_by_default():
    assert _run_on_fixture(
        ThreadHazardChecker, "device_registry_bad.py") == []
    assert _run_on_fixture(LockOrderChecker, "device_registry_bad.py") == []


# ----------------------------------------------------------- suppression

def _no_print_over(tmp_path, source):
    path = tmp_path / "mod.py"
    path.write_text(source)
    return gc.run_checkers([NoPrintChecker], str(path), str(tmp_path))


def test_inline_suppression_drops_finding(tmp_path):
    src = 'print("x")  # graftcheck: disable=no-print\n'
    assert _no_print_over(tmp_path, src) == []


def test_standalone_comment_suppresses_next_line(tmp_path):
    src = ('# tooling speaks over stdout; graftcheck: disable=no-print\n'
           'print("x")\n')
    assert _no_print_over(tmp_path, src) == []


def test_disable_all_suppresses_every_checker(tmp_path):
    src = 'print("x")  # graftcheck: disable=all\n'
    assert _no_print_over(tmp_path, src) == []


def test_unsuppressed_line_still_fires(tmp_path):
    src = ('print("a")  # graftcheck: disable=no-print\n'
           'print("b")\n')
    findings = _no_print_over(tmp_path, src)
    assert [f.line for f in findings] == [2]


def test_suppression_for_other_checker_does_not_apply(tmp_path):
    src = 'print("x")  # graftcheck: disable=determinism\n'
    findings = _no_print_over(tmp_path, src)
    assert len(findings) == 1


def test_suppression_applies_to_new_checker_ids(tmp_path):
    src = ("import jax\n"
           "def f(x):\n"
           "    if jax.process_index() == 0:\n"
           "        # single-host warmup subtree, never multi-process\n"
           "        return jax.lax.psum(x, 'data')"
           "  # graftcheck: disable=collective-deadlock\n"
           "    return x\n")
    path = tmp_path / "mod.py"
    path.write_text(src)
    findings = gc.run_checkers(
        [CollectiveDeadlockChecker], str(path), str(tmp_path))
    assert findings == []


def test_standalone_suppression_on_new_checker_ids(tmp_path):
    src = ("import jax\n"
           "step = jax.jit(lambda p, b: p, donate_argnums=(0,))\n"
           "def f(p, b):\n"
           "    out = step(p, b)\n"
           "    # donation is a no-op on the CPU-only debug path here\n"
           "    # graftcheck: disable=donation-safety\n"
           "    return out, p\n")
    path = tmp_path / "mod.py"
    path.write_text(src)
    findings = gc.run_checkers(
        [DonationSafetyChecker], str(path), str(tmp_path))
    assert findings == []
    # and without the directive the same source fires
    path.write_text(src.replace("    # graftcheck: disable=donation-safety\n",
                                ""))
    findings = gc.run_checkers(
        [DonationSafetyChecker], str(path), str(tmp_path))
    assert [f.key for f in findings] == ["f:use-after-donate:p:step"]


# -------------------------------------------------------------- baseline

def test_baseline_round_trip(tmp_path):
    findings = _run_on_fixture(DeterminismChecker, "determinism_bad.py")
    assert findings
    path = tmp_path / "baseline.json"
    gc.write_baseline(findings, str(path))
    baseline = gc.load_baseline(str(path))
    new, old, stale = gc.apply_baseline(findings, baseline)
    assert new == [] and stale == []
    assert {f.fingerprint for f in old} == set(baseline)

    # dropping one entry resurfaces exactly that finding as new
    new, _old, _stale = gc.apply_baseline(findings, baseline[1:])
    assert [f.fingerprint for f in new] == [baseline[0]]

    # a fingerprint matching nothing is reported stale, never fatal
    _new, _old, stale = gc.apply_baseline(findings, baseline + ["bogus:x:y"])
    assert stale == ["bogus:x:y"]


def test_baseline_file_is_one_fingerprint_per_line(tmp_path):
    findings = _run_on_fixture(NoPrintChecker, "no_print_bad.py")
    path = tmp_path / "baseline.json"
    gc.write_baseline(findings, str(path))
    lines = path.read_text().splitlines()
    # [ ... one quoted fingerprint per interior line ... ]
    assert lines[0] == "[" and lines[-1] == "]"
    assert len(lines) == 2 + len({f.fingerprint for f in findings})


def test_fingerprints_are_line_number_free():
    findings = _run_on_fixture(NoPrintChecker, "no_print_bad.py")
    for f in findings:
        assert str(f.line) not in f.fingerprint.split(":")[-1] or f.line > 99


# --------------------------------------------------------------- frontend

def test_cli_analyze_exits_zero():
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-m", "fedml_tpu.cli", "analyze"],
        cwd=REPO_ROOT, env=env, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "graftcheck:" in proc.stdout


def test_json_output_shape(tmp_path, capsys):
    bad = os.path.join(FIXTURES, "no_print_bad.py")
    rc = gc.main(["--json", "--no-baseline", "--checker", "no-print",
                  "--root", bad])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert out["checkers"] == ["no-print"]
    assert len(out["new"]) == 1
    finding = out["new"][0]
    assert set(finding) == {"checker", "path", "line", "severity",
                            "message", "fingerprint"}


def test_sarif_output_shape(capsys):
    bad = os.path.join(FIXTURES, "no_print_bad.py")
    rc = gc.main(["--format", "sarif", "--no-baseline",
                  "--checker", "no-print", "--root", bad])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert doc["version"] == "2.1.0"
    assert doc["$schema"].endswith("sarif-schema-2.1.0.json")
    run = doc["runs"][0]
    driver = run["tool"]["driver"]
    assert driver["name"] == "graftcheck"
    # one rule per registered checker, findings or not
    assert {r["id"] for r in driver["rules"]} == set(gc.checker_registry())
    result = run["results"][0]
    assert result["ruleId"] == "no-print"
    assert result["level"] in ("error", "warning")
    assert result["message"]["text"]
    loc = result["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uri"].endswith("no_print_bad.py")
    assert loc["region"]["startLine"] >= 1
    # baseline identity rides along for CI dedup across pushes
    assert result["partialFingerprints"]["graftcheck/v1"].startswith("no-print:")


def test_sarif_clean_run_exits_zero(capsys):
    clean = os.path.join(FIXTURES, "no_print_clean.py")
    rc = gc.main(["--format", "sarif", "--no-baseline",
                  "--checker", "no-print", "--root", clean])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert doc["runs"][0]["results"] == []


def test_changed_files_returns_existing_py_paths():
    changed = gc.changed_files(REPO_ROOT, "HEAD")
    assert isinstance(changed, list)
    for path in changed:
        assert path.endswith(".py") and os.path.exists(path)


def test_changed_only_run_completes(capsys):
    # whatever the working tree looks like, the dev loop must terminate
    # cleanly: either "nothing changed" or a normal (possibly red) run
    rc = gc.main(["--changed-only", "HEAD", "--no-baseline",
                  "--checker", "no-print"])
    out = capsys.readouterr().out
    assert rc in (0, 1)
    assert "graftcheck:" in out


def test_changed_only_skips_whole_package_checkers(capsys):
    # config-drift over a partial scan would report every unchanged key as
    # doc-only drift — it must be excluded from the dev loop
    rc = gc.main(["--changed-only", "HEAD", "--no-baseline"])
    out = capsys.readouterr().out
    assert rc in (0, 1)
    if "no .py files changed" not in out:
        assert "skipping whole-package checker(s)" in out
        assert "config-drift" not in out.split("[checkers:")[-1]


def test_checker_registry_is_complete():
    assert sorted(gc.checker_registry()) == [
        "collective-deadlock", "config-drift", "determinism",
        "donation-safety", "host-sync", "jit-purity", "lock-order",
        "no-print", "resource-leak", "retrace-hazard",
        "sharding-consistency", "thread-hazard", "wire-protocol"]


# -------------------------------------------------------- retrace-hazard

def test_retrace_hazard_fires_on_bad_fixture():
    findings = _run_on_fixture(RetraceHazardChecker, "retrace_hazard_bad.py")
    keys = {f.key for f in findings}
    assert "jit_in_loop:jit-in-loop:step" in keys
    assert "per_call_jit:per-call-jit:step" in keys
    assert "loop_varying_static:static-loop-varying:compiled:1" in keys
    assert "unhashable_static:unhashable-static:compiled:1" in keys
    assert "shape_flow:shape-flow:plain" in keys
    # a retrace inside a lax.scan block body recompiles the whole fused
    # dispatch — the PR 15 scope gets its own key
    assert "scan_block.body:scan-body-jit:step" in keys
    # bound-but-never-invoked wrapper is a warning, not an error
    discarded = [f for f in findings
                 if f.key == "discarded_jit:per-call-jit:step"]
    assert discarded and discarded[0].severity == "warning"


def test_retrace_hazard_silent_on_clean_fixture():
    assert _run_on_fixture(RetraceHazardChecker,
                           "retrace_hazard_clean.py") == []


# --------------------------------------------------------- wire-protocol

def _run_on_fixture_set(checker_cls, filenames):
    """Whole-package checker over several fixture files sharing one
    project graph (the shape run_checkers provides)."""
    from fedml_tpu.analysis.project import build_graph

    mods = [gc.load_module(os.path.join(FIXTURES, fn), FIXTURES)
            for fn in filenames]
    ctx = gc.Context(repo_root=FIXTURES, package_dir=FIXTURES)
    ctx.graph = build_graph(mods)
    checker = checker_cls(ctx)
    findings = []
    for mod in mods:
        if checker.interested(mod.relpath):
            findings.extend(checker.visit_module(mod))
    findings.extend(checker.finalize())
    return findings


def test_wire_protocol_fires_on_bad_fixture():
    findings = _run_on_fixture(WireProtocolChecker, "wire_protocol_bad.py")
    keys = {f.key for f in findings}
    assert "unhandled-send:MSG_TYPE_ORPHANED" in keys
    assert "unstamped-key:MSG_TYPE_UPLOAD:'model_version'" in keys
    assert any(k.startswith("raw-literal:") and "'num_samples'" in k
               for k in keys)


def test_wire_protocol_silent_on_clean_fixture():
    assert _run_on_fixture(WireProtocolChecker,
                           "wire_protocol_clean.py") == []


def test_wire_protocol_flags_duplicated_constant_across_modules():
    # both fixtures define MSG_TYPE_SHARED = "shared_event"; the checker
    # flags every copy except the sorted-first canonical one
    findings = _run_on_fixture_set(
        WireProtocolChecker,
        ["wire_protocol_bad.py", "wire_protocol_clean.py"])
    dups = [f for f in findings if f.key == "dup-constant:MSG_TYPE_SHARED"]
    assert len(dups) == 1
    assert dups[0].path.endswith("wire_protocol_clean.py")
    assert dups[0].severity == "warning"


# --------------------------------------------------------- resource-leak

def test_resource_leak_fires_on_bad_fixture():
    findings = _run_on_fixture(ResourceLeakChecker, "resource_leak_bad.py")
    keys = {f.key for f in findings}
    assert "thread_never_joined:thread-no-join:t" in keys
    assert "inline_thread:thread-no-join:<inline>" in keys
    assert "unclosed_file:unclosed:file:f" in keys
    assert "inline_open:unclosed:file:<inline>" in keys
    assert "unclosed_socket:unclosed:socket:s" in keys
    assert "unclosed_channel:unclosed:grpc-channel:ch" in keys
    assert "spill-no-reclaim" in keys


def test_resource_leak_silent_on_clean_fixture():
    assert _run_on_fixture(ResourceLeakChecker,
                           "resource_leak_clean.py") == []


# ------------------------------------------------------ incremental cache

def test_cache_cold_and_warm_runs_are_byte_identical(tmp_path, capsys):
    cache = str(tmp_path / "cache.json")
    rc_cold = gc.main(["--json", "--cache", cache])
    cold = capsys.readouterr().out
    assert os.path.exists(cache)
    rc_warm = gc.main(["--json", "--cache", cache])
    warm = capsys.readouterr().out
    assert rc_cold == rc_warm
    assert cold == warm, "warm cache run must reproduce the cold run exactly"


def test_cache_warm_run_is_fast(tmp_path, capsys):
    import time

    cache = str(tmp_path / "cache.json")
    gc.main(["--json", "--cache", cache])  # cold: populate
    capsys.readouterr()
    t0 = time.perf_counter()
    gc.main(["--json", "--cache", cache])
    assert time.perf_counter() - t0 < 10.0, "warm path must skip parsing"
    capsys.readouterr()


def test_cache_invalidates_on_file_change(tmp_path, capsys):
    # a package copy with one bad file: fixing the file must flip the
    # cached verdict (content-hash invalidation, not mtime)
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    bad = pkg / "mod.py"
    bad.write_text("def f():\n    print('hi')\n")
    from fedml_tpu.analysis.cache import run_checkers_cached

    cache = str(tmp_path / "cache.json")
    registry = gc.checker_registry()
    classes = [registry["no-print"]]
    first = run_checkers_cached(classes, str(pkg), str(tmp_path), cache)
    assert len(first) == 1 and first[0].checker == "no-print"
    bad.write_text("def f():\n    return 'hi'\n")
    second = run_checkers_cached(classes, str(pkg), str(tmp_path), cache)
    assert second == []


def test_stats_report_timing_and_hit_rate(tmp_path, capsys):
    cache = str(tmp_path / "cache.json")
    gc.main(["--stats", "--json", "--cache", cache])
    capsys.readouterr()
    gc.main(["--stats", "--json", "--cache", cache])
    err = capsys.readouterr().err
    assert "graftcheck stats:" in err
    assert "cache hit rate 100.0%" in err
    assert "jit-purity" in err


# ----------------------------------------------- changed-only improvements

def _git(repo, *args):
    subprocess.run(["git", *args], cwd=repo, check=True,
                   capture_output=True, text=True)


def test_changed_files_follows_renames(tmp_path):
    repo = str(tmp_path)
    _git(repo, "init", "-q")
    _git(repo, "config", "user.email", "t@t")
    _git(repo, "config", "user.name", "t")
    (tmp_path / "old_name.py").write_text("X = 1\n")
    _git(repo, "add", "old_name.py")
    _git(repo, "commit", "-qm", "seed")
    _git(repo, "mv", "old_name.py", "new_name.py")
    changed = gc.changed_files(repo, "HEAD")
    # the rename must surface the NEW path, not the dead old one
    assert any(p.endswith("new_name.py") for p in changed)
    assert not any(p.endswith("old_name.py") for p in changed)


def test_changed_files_skips_deletions(tmp_path):
    repo = str(tmp_path)
    _git(repo, "init", "-q")
    _git(repo, "config", "user.email", "t@t")
    _git(repo, "config", "user.name", "t")
    (tmp_path / "doomed.py").write_text("X = 1\n")
    _git(repo, "add", "doomed.py")
    _git(repo, "commit", "-qm", "seed")
    _git(repo, "rm", "-q", "doomed.py")
    assert gc.changed_files(repo, "HEAD") == []


def test_expand_with_dependents_pulls_in_importers(tmp_path):
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "base.py").write_text("def helper():\n    return 1\n")
    (pkg / "user.py").write_text(
        "from pkg.base import helper\n\ndef g():\n    return helper()\n")
    (pkg / "loner.py").write_text("def h():\n    return 2\n")
    expanded = gc.expand_with_dependents(
        [str(pkg / "base.py")], str(pkg), str(tmp_path))
    names = {os.path.basename(p) for p in expanded}
    # editing base invalidates its importer's findings, not the loner's
    assert names == {"base.py", "user.py"}
