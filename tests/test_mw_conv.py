"""Multi-weight conv op (ops/conv.py): the packed-lane conv experiments.

Numerics: im2col and pallas (interpret mode on CPU) paths must match
lax.conv_general_dilated exactly — forward, grads, and the vmapped
multi-weight (per-lane x AND w) case that motivates the op.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fedml_tpu.ops.conv import Conv, conv2d_im2col, conv2d_pallas


def _ref(x, w, s=1, pad="SAME"):
    return jax.lax.conv_general_dilated(
        x, w, (s, s), pad, dimension_numbers=("NHWC", "HWIO", "NHWC"))


@pytest.fixture()
def interp_pallas(monkeypatch):
    from jax.experimental import pallas as pl

    monkeypatch.setattr(
        pl, "pallas_call", functools.partial(pl.pallas_call, interpret=True))


@pytest.mark.parametrize("shape", [
    (2, 8, 8, 5, 7, 3, 1, "SAME"),
    (2, 9, 9, 4, 6, 3, 2, "SAME"),
    (2, 8, 8, 3, 4, 1, 1, "SAME"),
    (2, 8, 8, 3, 4, 1, 2, "SAME"),
    (2, 10, 10, 4, 4, 3, 1, "VALID"),
    (2, 11, 11, 2, 3, 5, 2, "SAME"),
])
def test_im2col_matches_lax_conv(shape):
    b, h, ww, ci, co, k, s, pad = shape
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(b, h, ww, ci), jnp.float32)
    w = jnp.asarray(rng.randn(k, k, ci, co), jnp.float32) * 0.1
    got, want = conv2d_im2col(x, w, s, pad), _ref(x, w, s, pad)
    assert got.shape == want.shape
    np.testing.assert_allclose(got, want, atol=1e-4)


def test_im2col_grads_match():
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(2, 8, 8, 5), jnp.float32)
    w = jnp.asarray(rng.randn(3, 3, 5, 7), jnp.float32) * 0.1
    g1 = jax.grad(lambda x, w: (conv2d_im2col(x, w) ** 2).sum(), (0, 1))(x, w)
    g2 = jax.grad(lambda x, w: (_ref(x, w) ** 2).sum(), (0, 1))(x, w)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(a, b, atol=1e-3)


def test_pallas_fwd_bwd_match(interp_pallas):
    rng = np.random.RandomState(2)
    x = jnp.asarray(rng.randn(4, 8, 8, 5), jnp.float32)
    w = jnp.asarray(rng.randn(3, 3, 5, 7), jnp.float32) * 0.1
    np.testing.assert_allclose(conv2d_pallas(x, w, 1, "SAME"), _ref(x, w),
                               atol=1e-4)
    g1 = jax.grad(lambda x, w: (conv2d_pallas(x, w, 1, "SAME") ** 2).sum(),
                  (0, 1))(x, w)
    g2 = jax.grad(lambda x, w: (_ref(x, w) ** 2).sum(), (0, 1))(x, w)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(a, b, atol=1e-3)


def test_pallas_vmapped_multiweight_matches(interp_pallas):
    """The motivating case: vmap over BOTH x and w (per-lane weights) —
    pallas prepends the mapped axis to its grid; custom_vjp must stay
    correct under the same mapping."""
    rng = np.random.RandomState(3)
    L = 3
    xs = jnp.asarray(rng.randn(L, 4, 8, 8, 5), jnp.float32)
    ws = jnp.asarray(rng.randn(L, 3, 3, 5, 7), jnp.float32) * 0.1

    def f(conv):
        return lambda xs, ws: (
            jax.vmap(lambda x, w: conv(x, w, 1, "SAME"))(xs, ws) ** 2).sum()

    np.testing.assert_allclose(
        jax.vmap(lambda x, w: conv2d_pallas(x, w, 1, "SAME"))(xs, ws),
        jax.vmap(_ref)(xs, ws), atol=1e-4)
    g1 = jax.grad(f(conv2d_pallas), (0, 1))(xs, ws)
    g2 = jax.grad(f(lambda x, w, s, p: _ref(x, w, s, p)), (0, 1))(xs, ws)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(a, b, atol=1e-3)


def test_conv_module_param_tree_matches_nn_conv():
    """Conv (ops/conv.py) must be a drop-in for nn.Conv: same auto-name,
    same param shapes/dtypes, so checkpoints transfer across conv_impl."""
    import flax.linen as nn

    class MX(nn.Module):
        @nn.compact
        def __call__(self, x):
            return nn.Conv(8, (3, 3), use_bias=False)(x)

    class MM(nn.Module):
        @nn.compact
        def __call__(self, x):
            return Conv(8, (3, 3), impl="im2col")(x)

    x = jnp.zeros((1, 8, 8, 3))
    px = MX().init(jax.random.PRNGKey(0), x)
    pm = MM().init(jax.random.PRNGKey(0), x)
    sx = jax.tree_util.tree_map(lambda a: (a.shape, a.dtype), px)
    sm = jax.tree_util.tree_map(lambda a: (a.shape, a.dtype), pm)
    assert sx == sm


def test_resnet_conv_impl_variants_agree():
    """CifarResNet forward must be numerically identical (tolerance) across
    conv_impl, with the SAME param tree."""
    from fedml_tpu.models.resnet import CifarResNet

    rng = np.random.RandomState(4)
    x = jnp.asarray(rng.randn(2, 32, 32, 3), jnp.float32)
    m_xla = CifarResNet(depth=20, num_classes=10, conv_impl="xla")
    m_im = CifarResNet(depth=20, num_classes=10, conv_impl="im2col")
    p = m_xla.init(jax.random.PRNGKey(0), x)
    y1 = m_xla.apply(p, x)
    y2 = m_im.apply(p, x)      # same params work across impls
    np.testing.assert_allclose(y1, y2, atol=2e-4)
