"""Example-level DP-SGD: clipping bounds the update, noise engages, the
accountant behaves, and DP training still learns (reference core/dp is an
empty stub — SURVEY.md §2.1; this is the real mechanism)."""

import jax
import numpy as np

import fedml_tpu
from fedml_tpu.algorithms import LocalTrainConfig, make_local_update
from fedml_tpu.core import epsilon_for_training, rdp_epsilon
from fedml_tpu.simulation import build_simulator


def _client_data(scale=1.0, n=16, d=8, seed=0):
    rng = np.random.default_rng(seed)
    x = (scale * rng.normal(size=(1, n, d))).astype(np.float32)
    y = rng.integers(0, 2, size=(1, n)).astype(np.int32)
    return {
        "x": x, "y": y, "mask": np.ones((1, n), np.float32),
        "num_samples": np.int32(n),
    }


def _apply_fn():
    import flax.linen as nn
    import jax.numpy as jnp

    model = nn.Dense(2)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8)))

    def apply_fn(p, x, train=False, rngs=None, mutable=False):
        return model.apply(p, x)

    return apply_fn, params


def _update_norm(update):
    return float(np.sqrt(sum(
        float((np.asarray(g) ** 2).sum()) for g in jax.tree.leaves(update)
    )))


def test_dp_clipping_bounds_the_step():
    """One SGD step, lr=1, clip=C, no noise: ||delta|| <= C (per-example
    clipped mean can never exceed the clip), while the unclipped update on
    outlier data far exceeds it."""
    apply_fn, params = _apply_fn()
    data = _client_data(scale=100.0)  # outlier client
    rng = jax.random.PRNGKey(0)

    plain = make_local_update(apply_fn, LocalTrainConfig(lr=1.0, epochs=1))
    out_plain = plain(params, (), data, rng)
    clipped = make_local_update(apply_fn, LocalTrainConfig(
        lr=1.0, epochs=1, dp_l2_clip=0.5, dp_noise_multiplier=0.0))
    out_dp = clipped(params, (), data, rng)

    assert _update_norm(out_plain.update) > 5.0
    assert _update_norm(out_dp.update) <= 0.5 + 1e-5


def test_dp_noise_engages_and_is_seeded():
    apply_fn, params = _apply_fn()
    data = _client_data()
    cfg = LocalTrainConfig(lr=0.1, epochs=1, dp_l2_clip=1.0,
                           dp_noise_multiplier=1.0)
    upd = make_local_update(apply_fn, cfg)
    a = upd(params, (), data, jax.random.PRNGKey(1))
    b = upd(params, (), data, jax.random.PRNGKey(2))
    same = upd(params, (), data, jax.random.PRNGKey(1))
    la, lb = jax.tree.leaves(a.update), jax.tree.leaves(b.update)
    assert any(not np.allclose(np.asarray(x), np.asarray(y)) for x, y in zip(la, lb))
    for x, y in zip(la, jax.tree.leaves(same.update)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_rdp_accountant_monotonicity():
    assert rdp_epsilon(0.0, 10) == float("inf")
    e1 = rdp_epsilon(1.0, 100)
    e2 = rdp_epsilon(1.0, 400)
    e3 = rdp_epsilon(4.0, 100)
    assert e1 < e2          # more steps -> more privacy loss
    assert e3 < e1          # more noise -> less privacy loss
    assert epsilon_for_training(1.0, 10, 10) == rdp_epsilon(1.0, 100)


def test_dp_training_still_learns():
    """End-to-end: federated LR on synthetic MNIST with DP-SGD still reaches
    useful accuracy; the run's eps is finite."""
    args = fedml_tpu.init(config=dict(
        dataset="mnist", model="lr", debug_small_data=True,
        client_num_in_total=10, client_num_per_round=10, comm_round=20,
        learning_rate=0.2, epochs=1, batch_size=32,
        frequency_of_the_test=19, random_seed=0,
        dp_l2_clip=2.0, dp_noise_multiplier=0.1,
    ))
    sim, apply_fn = build_simulator(args)
    hist = sim.run(apply_fn, log_fn=None)
    assert hist[-1]["test_acc"] > 0.7, hist[-1]
    eps = epsilon_for_training(0.1, comm_rounds=20,
                               steps_per_round=sim.num_local_batches)
    assert np.isfinite(eps)
